"""SNAT differential tests: pod→external egress leaves with the node IP,
replies translate back, counters account the translations.

Reference analog: the service configurator's SNAT pool for traffic
leaving the cluster (plugins/service/configurator/configurator_impl.go
:258-264) applied by VPP's nat44 in2out/out2in nodes.
"""

import jax.numpy as jnp
import numpy as np

from vpp_tpu.pipeline.graph import pipeline_step
from vpp_tpu.pipeline.tables import DataplaneConfig, InterfaceType, TableBuilder
from vpp_tpu.pipeline.vector import (
    Disposition,
    ip4,
    ip4_str,
    make_packet_vector,
)

IF_POD, IF_UPLINK = 0, 1
POD_IP = "10.1.1.2"
NODE_IP = "192.168.16.1"
EXT_IP = "93.184.216.34"


def snat_builder():
    b = TableBuilder(DataplaneConfig())
    b.set_interface(IF_POD, InterfaceType.POD)
    b.set_interface(IF_UPLINK, InterfaceType.UPLINK)
    b.add_route(f"{POD_IP}/32", IF_POD, Disposition.LOCAL)
    # Cluster-egress default route: SNAT applies.
    b.add_route("0.0.0.0/0", IF_UPLINK, Disposition.REMOTE,
                next_hop=ip4("192.168.16.100"), snat=True)
    b.nat_snat_ip = np.uint32(ip4(NODE_IP))
    return b


def test_snat_egress_and_reply_roundtrip():
    t = snat_builder().to_device()
    # pod → external: source must leave as the node IP.
    out = pipeline_step(t, make_packet_vector(
        [{"src": POD_IP, "dst": EXT_IP, "proto": 6,
          "sport": 44321, "dport": 443, "rx_if": IF_POD}]
    ), jnp.int32(1))
    assert int(out.disp[0]) == Disposition.REMOTE
    assert ip4_str(out.pkts.src_ip[0]) == NODE_IP
    alloc_port = int(out.pkts.sport[0])
    assert 1024 <= alloc_port < 65536
    assert bool(out.snat_applied[0])
    assert int(out.stats.snat) == 1
    assert int(out.stats.dnat) == 0

    # reply external → node IP:alloc — must un-SNAT to the pod and be
    # delivered on the pod interface without any pod-side permit rule
    # (reflective session admits it).
    rep = pipeline_step(out.tables, make_packet_vector(
        [{"src": EXT_IP, "dst": NODE_IP, "proto": 6,
          "sport": 443, "dport": alloc_port, "rx_if": IF_UPLINK}]
    ), jnp.int32(2))
    assert int(rep.disp[0]) == Disposition.LOCAL
    assert int(rep.tx_if[0]) == IF_POD
    assert ip4_str(rep.pkts.dst_ip[0]) == POD_IP
    assert int(rep.pkts.dport[0]) == 44321
    assert int(rep.stats.nat_reversed) == 1


def test_snat_port_is_flow_consistent():
    t = snat_builder().to_device()
    pkts = make_packet_vector(
        [{"src": POD_IP, "dst": EXT_IP, "proto": 6,
          "sport": 50000, "dport": 443, "rx_if": IF_POD}] * 3
        + [{"src": POD_IP, "dst": EXT_IP, "proto": 6,
            "sport": 50001, "dport": 443, "rx_if": IF_POD}]
    )
    out = pipeline_step(t, pkts, jnp.int32(1))
    ports = [int(out.pkts.sport[i]) for i in range(4)]
    assert ports[0] == ports[1] == ports[2]  # same flow → same port
    # a different flow must actually be translated (not passthrough)
    assert 1024 <= ports[3] < 65536
    assert ports[3] != 50001


def test_snat_skips_local_and_non_marked_routes():
    b = snat_builder()
    b.add_route("10.2.0.0/16", IF_UPLINK, Disposition.REMOTE, node_id=2)
    t = b.to_device()
    pkts = make_packet_vector(
        [  # pod → other-node pod subnet: fabric route, NOT snat-marked
            {"src": POD_IP, "dst": "10.2.0.9", "proto": 6,
             "sport": 1000, "dport": 80, "rx_if": IF_POD},
        ]
    )
    out = pipeline_step(t, pkts, jnp.int32(1))
    assert int(out.disp[0]) == Disposition.REMOTE
    assert ip4_str(out.pkts.src_ip[0]) == POD_IP
    assert int(out.stats.snat) == 0


def test_nodeport_dnat_plus_snat_combined():
    """External client → nodeIP:nodeport, backend behind an SNAT-marked
    route: forward carries DNAT+SNAT, the reply undoes both."""
    b = snat_builder()
    # nodeport mapping on the node IP toward a backend reached over the
    # default (snat-marked) route — the remote-backend nodeport case.
    backend = "93.99.0.5"
    b.set_nat_mapping(
        0, ext_ip=ip4(NODE_IP), ext_port=30080, proto=6,
        backends=[(ip4(backend), 8080, 1)], boff=0,
    )
    t = b.to_device()
    client = "198.51.100.7"
    out = pipeline_step(t, make_packet_vector(
        [{"src": client, "dst": NODE_IP, "proto": 6,
          "sport": 7777, "dport": 30080, "rx_if": IF_UPLINK}]
    ), jnp.int32(1))
    assert bool(out.dnat_applied[0]) and bool(out.snat_applied[0])
    assert ip4_str(out.pkts.dst_ip[0]) == backend
    assert int(out.pkts.dport[0]) == 8080
    assert ip4_str(out.pkts.src_ip[0]) == NODE_IP
    alloc = int(out.pkts.sport[0])
    assert int(out.stats.dnat) == 1 and int(out.stats.snat) == 1

    # backend reply → must become (nodeIP:30080 → client:7777)
    rep = pipeline_step(out.tables, make_packet_vector(
        [{"src": backend, "dst": NODE_IP, "proto": 6,
          "sport": 8080, "dport": alloc, "rx_if": IF_UPLINK}]
    ), jnp.int32(2))
    assert ip4_str(rep.pkts.src_ip[0]) == NODE_IP
    assert int(rep.pkts.sport[0]) == 30080
    assert ip4_str(rep.pkts.dst_ip[0]) == client
    assert int(rep.pkts.dport[0]) == 7777
    assert int(rep.disp[0]) == Disposition.REMOTE  # back out the uplink
    assert int(rep.stats.nat_reversed) == 1


def test_nodeport_remote_backend_self_snat():
    """Nodeport mapping marked self-snat: DNAT to a backend behind a
    NON-snat fabric route still gets SNAT'd so the reply returns here
    (the round-1 asymmetry: replies used to bypass the ingress node)."""
    b = snat_builder()
    backend = "10.2.0.5"  # on peer node 2, plain fabric route
    b.add_route("10.2.0.0/16", IF_UPLINK, Disposition.REMOTE, node_id=2)
    b.set_nat_mapping(
        0, ext_ip=ip4(NODE_IP), ext_port=30080, proto=6,
        backends=[(ip4(backend), 8080, 1)], boff=0, self_snat=True,
    )
    t = b.to_device()
    client = "198.51.100.7"
    out = pipeline_step(t, make_packet_vector(
        [{"src": client, "dst": NODE_IP, "proto": 6,
          "sport": 7777, "dport": 30080, "rx_if": IF_UPLINK}]
    ), jnp.int32(1))
    assert bool(out.dnat_applied[0]) and bool(out.snat_applied[0])
    assert ip4_str(out.pkts.src_ip[0]) == NODE_IP  # SNAT despite fabric route
    assert int(out.node_id[0]) == 2
    alloc = int(out.pkts.sport[0])

    rep = pipeline_step(out.tables, make_packet_vector(
        [{"src": backend, "dst": NODE_IP, "proto": 6,
          "sport": 8080, "dport": alloc, "rx_if": IF_UPLINK}]
    ), jnp.int32(2))
    assert ip4_str(rep.pkts.src_ip[0]) == NODE_IP
    assert int(rep.pkts.sport[0]) == 30080
    assert ip4_str(rep.pkts.dst_ip[0]) == client
    assert int(rep.pkts.dport[0]) == 7777


def test_icmp_snat_and_unsupported_proto_drop():
    t = snat_builder().to_device()
    out = pipeline_step(t, make_packet_vector(
        [  # icmp echo: src-only SNAT, id (sport/dport) untouched
            {"src": POD_IP, "dst": EXT_IP, "proto": 1,
             "sport": 321, "dport": 321, "rx_if": IF_POD},
            # GRE: not NAT-able → fail closed on the SNAT route
            {"src": POD_IP, "dst": EXT_IP, "proto": 47,
             "sport": 0, "dport": 0, "rx_if": IF_POD},
        ]
    ), jnp.int32(1))
    assert ip4_str(out.pkts.src_ip[0]) == NODE_IP
    assert int(out.pkts.sport[0]) == 321  # echo id preserved
    assert int(out.disp[0]) == Disposition.REMOTE
    assert int(out.disp[1]) == Disposition.DROP
    assert int(out.stats.drop_nat) == 1

    # echo reply round-trips back to the pod
    rep = pipeline_step(out.tables, make_packet_vector(
        [{"src": EXT_IP, "dst": NODE_IP, "proto": 1,
          "sport": 321, "dport": 321, "rx_if": IF_UPLINK}]
    ), jnp.int32(2))
    assert int(rep.disp[0]) == Disposition.LOCAL
    assert ip4_str(rep.pkts.dst_ip[0]) == POD_IP


def test_snat_port_collision_fails_closed():
    """Force a reply-key collision: two flows whose SNAT'd reply
    5-tuples are identical must not both own the NAT session — the
    second flow drops and is counted, never misdelivered."""
    import numpy as np

    from vpp_tpu.ops.nat44 import _flow_hash

    b = snat_builder()
    t = b.to_device()
    # find two (sport) values from different pods that hash to the same
    # allocated port toward the same external endpoint

    pod2 = "10.1.1.3"
    b2 = snat_builder()
    b2.add_route(f"{pod2}/32", IF_POD, Disposition.LOCAL)
    t = b2.to_device()

    def alloc_port_of(src, sport):
        pv = make_packet_vector(
            [{"src": src, "dst": EXT_IP, "proto": 6,
              "sport": sport, "dport": 443, "rx_if": IF_POD}]
        )
        return 1024 + int(np.asarray(_flow_hash(pv)[0])) % 64512

    base = alloc_port_of(POD_IP, 40000)
    match = None
    for sp in range(40000, 60000):
        if alloc_port_of(pod2, sp) == base:
            match = sp
            break
    assert match is not None, "no colliding sport found in range"

    out1 = pipeline_step(t, make_packet_vector(
        [{"src": POD_IP, "dst": EXT_IP, "proto": 6,
          "sport": 40000, "dport": 443, "rx_if": IF_POD}]
    ), jnp.int32(1))
    assert int(out1.stats.snat) == 1
    out2 = pipeline_step(out1.tables, make_packet_vector(
        [{"src": pod2, "dst": EXT_IP, "proto": 6,
          "sport": match, "dport": 443, "rx_if": IF_POD}]
    ), jnp.int32(2))
    assert int(out2.disp[0]) == Disposition.DROP
    assert int(out2.stats.drop_nat) == 1

    # the original flow's reply still translates to the right pod
    rep = pipeline_step(out2.tables, make_packet_vector(
        [{"src": EXT_IP, "dst": NODE_IP, "proto": 6,
          "sport": 443, "dport": base, "rx_if": IF_UPLINK}]
    ), jnp.int32(3))
    assert ip4_str(rep.pkts.dst_ip[0]) == POD_IP
    assert int(rep.pkts.dport[0]) == 40000


def test_snat_port_collision_intra_batch_fails_closed():
    """Two colliding flows in the SAME packet vector: exactly one owns
    the NAT session; the other drops (never silently misdelivered)."""
    import numpy as np

    from vpp_tpu.ops.nat44 import _flow_hash

    pod2 = "10.1.1.3"
    b = snat_builder()
    b.add_route(f"{pod2}/32", IF_POD, Disposition.LOCAL)
    t = b.to_device()

    def alloc_port_of(src, sport):
        pv = make_packet_vector(
            [{"src": src, "dst": EXT_IP, "proto": 6,
              "sport": sport, "dport": 443, "rx_if": IF_POD}]
        )
        return 1024 + int(np.asarray(_flow_hash(pv)[0])) % 64512

    base = alloc_port_of(POD_IP, 40000)
    match = next(
        (sp for sp in range(40000, 60000) if alloc_port_of(pod2, sp) == base),
        None,
    )
    assert match is not None, "no colliding sport found in range"

    out = pipeline_step(t, make_packet_vector(
        [{"src": POD_IP, "dst": EXT_IP, "proto": 6,
          "sport": 40000, "dport": 443, "rx_if": IF_POD},
         {"src": pod2, "dst": EXT_IP, "proto": 6,
          "sport": match, "dport": 443, "rx_if": IF_POD}]
    ), jnp.int32(1))
    disps = [int(out.disp[i]) for i in range(2)]
    assert sorted(disps) == [int(Disposition.DROP), int(Disposition.REMOTE)]
    assert int(out.stats.drop_nat) == 1
    winner = disps.index(int(Disposition.REMOTE))
    winner_pod = POD_IP if winner == 0 else pod2
    winner_sport = 40000 if winner == 0 else match

    # the reply translates to the winner, never the loser
    rep = pipeline_step(out.tables, make_packet_vector(
        [{"src": EXT_IP, "dst": NODE_IP, "proto": 6,
          "sport": 443, "dport": base, "rx_if": IF_UPLINK}]
    ), jnp.int32(2))
    assert ip4_str(rep.pkts.dst_ip[0]) == winner_pod
    assert int(rep.pkts.dport[0]) == winner_sport


def test_snat_counters_account_translations():
    t = snat_builder().to_device()
    n = 32
    pkts = make_packet_vector(
        [{"src": POD_IP, "dst": EXT_IP, "proto": 6,
          "sport": 40000 + i, "dport": 443, "rx_if": IF_POD}
         for i in range(n)]
    )
    out = pipeline_step(t, pkts, jnp.int32(1))
    assert int(out.stats.snat) == n
    assert int(np.sum(np.asarray(out.tables.natsess_valid))) > 0
