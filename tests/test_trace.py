"""Packet tracer + cycle accounting tests.

Reference model: VPP `trace add` / `show trace` behavior — capture N
packets, show per-node path including drop point — and `show run`
per-node accounting (docs/VPP_PACKET_TRACING_K8S.md:20-50).
"""

import ipaddress

from vpp_tpu.ir import Action, ContivRule, Protocol
from vpp_tpu.pipeline.dataplane import Dataplane
from vpp_tpu.pipeline.tables import DataplaneConfig
from vpp_tpu.pipeline.vector import Disposition, ip4, make_packet_vector
from vpp_tpu.trace import PacketTracer, format_show_run, profile_stages


def wired_dp():
    dp = Dataplane(DataplaneConfig(sess_slots=256))
    uplink = dp.add_uplink()
    a = dp.add_pod_interface(("default", "a"))
    b = dp.add_pod_interface(("default", "b"))
    dp.builder.add_route("10.1.1.2/32", a, Disposition.LOCAL)
    dp.builder.add_route("10.1.1.3/32", b, Disposition.LOCAL)
    dp.builder.add_route("10.2.0.0/16", uplink, Disposition.REMOTE,
                         next_hop=ip4("192.168.16.2"), node_id=2)
    slot = dp.alloc_table_slot("t")
    dp.builder.set_local_table(slot, [
        ContivRule(action=Action.PERMIT,
                   dest_network=ipaddress.ip_network("10.1.1.3/32"),
                   protocol=Protocol.TCP, dest_port=80),
        ContivRule(action=Action.PERMIT,
                   dest_network=ipaddress.ip_network("10.2.0.0/16")),
        ContivRule(action=Action.DENY),
    ])
    dp.assign_pod_table(("default", "a"), "t")
    # VIP NAT for the dnat path
    dp.builder.set_nat_mapping(0, ext_ip=ip4("10.96.0.1"), ext_port=80,
                               proto=6, backends=[(ip4("10.1.1.3"), 80, 1)],
                               boff=0)
    dp.swap()
    return dp, a, b, uplink


def test_trace_paths_and_arming():
    dp, a, b, uplink = wired_dp()
    tracer = PacketTracer()
    assert tracer.record(dp.process(make_packet_vector(
        [dict(src="10.1.1.2", dst="10.1.1.3", proto=6, sport=1, dport=80,
              rx_if=a)]))) == 0, "not armed: nothing captured"

    tracer.add(10)
    frame = make_packet_vector([
        dict(src="10.1.1.2", dst="10.1.1.3", proto=6, sport=2, dport=80, rx_if=a),   # local ok
        dict(src="10.1.1.2", dst="10.1.1.3", proto=6, sport=3, dport=22, rx_if=a),   # acl deny
        dict(src="10.1.1.2", dst="10.2.9.9", proto=6, sport=4, dport=80, rx_if=a),   # remote
        dict(src="10.1.1.2", dst="10.96.0.1", proto=6, sport=5, dport=80, rx_if=a),  # via VIP
        dict(src="10.1.1.2", dst="10.9.9.9", proto=6, sport=6, dport=80, rx_if=a),   # no route→deny(acl)
        dict(src="10.1.1.2", dst="10.1.1.3", proto=6, sport=7, dport=80, ttl=0, rx_if=a),  # ttl drop
    ])
    captured = tracer.record(dp.process(frame))
    assert captured == 6
    e = tracer.entries()
    assert "interface-output (if %d)" % b in e[0].path
    assert e[1].drop_cause == "acl-deny"
    assert "error-drop (acl-deny)" in e[1].path
    assert "vxlan/ici-encap" in e[2].path and e[2].disposition == "REMOTE"
    assert "nat44-dnat" in e[3].path and e[3].dst == "10.1.1.3"
    assert e[4].drop_cause == "acl-deny"  # denied before lookup
    assert e[5].drop_cause == "ip4-input"
    assert "error-drop (ip4-input)" in e[5].path

    text = tracer.format_trace()
    assert "10.1.1.2 -> 10.1.1.3" in text
    assert "acl-deny" in text


def test_trace_established_return_flow():
    dp, a, b, uplink = wired_dp()
    dp.process(make_packet_vector(
        [dict(src="10.1.1.2", dst="10.1.1.3", proto=6, sport=999, dport=80,
              rx_if=a)]
    ))
    tracer = PacketTracer()
    tracer.add(1)
    res = dp.process(make_packet_vector(
        [dict(src="10.1.1.3", dst="10.1.1.2", proto=6, sport=80, dport=999,
              rx_if=b)]
    ))
    tracer.record(res)
    (e,) = tracer.entries()
    assert "session-lookup (established)" in e.path
    assert e.disposition == "LOCAL"


def test_trace_arming_counts_down_across_frames():
    dp, a, b, uplink = wired_dp()
    tracer = PacketTracer()
    tracer.add(3)
    frame = make_packet_vector([
        dict(src="10.1.1.2", dst="10.1.1.3", proto=6, sport=10 + i, dport=80,
             rx_if=a) for i in range(2)
    ])
    assert tracer.record(dp.process(frame)) == 2
    assert tracer.record(dp.process(frame)) == 1, "only 1 left armed"
    assert tracer.record(dp.process(frame)) == 0
    assert len(tracer.entries()) == 3
    tracer.clear()
    assert tracer.entries() == []


def test_dataplane_auto_records_when_tracer_attached():
    dp, a, b, uplink = wired_dp()
    tracer = PacketTracer()
    dp.tracer = tracer
    tracer.add(2)
    dp.process(make_packet_vector(
        [dict(src="10.1.1.2", dst="10.1.1.3", proto=6, sport=1, dport=80,
              rx_if=a)]
    ))
    assert len(tracer.entries()) == 1


def test_profile_stages_show_run():
    dp, a, b, uplink = wired_dp()
    frame = make_packet_vector([
        dict(src="10.1.1.2", dst="10.1.1.3", proto=6, sport=1, dport=80,
             rx_if=a)
    ])
    timings = profile_stages(dp.tables, frame, iters=2)
    names = {t.node for t in timings}
    assert "ip4-input" in names and "FUSED pipeline-step" in names
    assert all(t.seconds_per_call >= 0 for t in timings)
    table = format_show_run(timings)
    assert "ns/packet" in table and "acl-classify-local" in table
