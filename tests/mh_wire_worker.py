"""Worker for the multi-host WIRE e2e (run directly, not collected).

io.enabled multi-host: real wire frames (Ethernet/IP/UDP bytes) enter
one host's per-node rx ring, ride the fabric all_to_all — headers AND
payload — across the process boundary, and come out the destination
host's tx ring; then a renderer-driven deny cuts the path. The tick
loop drives the ClusterPump's dispatch so the wire step interleaves
deterministically with the lockstep driver's other collectives.
"""

import json
import logging
import os
import sys

import time

PROC_ID = int(sys.argv[1])
NUM_PROCS = int(sys.argv[2])
COORD_PORT = sys.argv[3]
KV_PORT = sys.argv[4]

if os.environ.get("MH_DEBUG"):
    logging.basicConfig(level=logging.INFO)
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(HERE))
sys.path.insert(0, HERE)  # tests/wire.py

import numpy as np  # noqa: E402

from vpp_tpu.parallel.multihost import (  # noqa: E402
    MultiHostRuntime, init_multihost,
)
from vpp_tpu.cmd import AgentConfig  # noqa: E402
from vpp_tpu.cmd.config import IOConfig  # noqa: E402
from vpp_tpu.cni.model import CNIRequest  # noqa: E402
from vpp_tpu.native.pktio import PacketCodec  # noqa: E402
from vpp_tpu.pipeline.vector import Disposition  # noqa: E402
from wire import make_frame  # noqa: E402

init_multihost(f"127.0.0.1:{COORD_PORT}", NUM_PROCS, PROC_ID,
               heartbeat_timeout_s=600)

cfg = AgentConfig(
    node_name="mhw", serve_http=False,
    store_url=f"tcp://127.0.0.1:{KV_PORT}",
    node_liveness_ttl_s=120.0,
    io=IOConfig(enabled=True, n_slots=16, snap=256),
)
runtime = MultiHostRuntime(4, cfg, tick_interval=0.02)
store = runtime.store
runtime.start()

SNAP = 256


def wait_for(pred, what, deadline_s=90.0):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        v = pred()
        if v:
            return v
        time.sleep(0.1)
    raise TimeoutError(f"waiting for {what}")


def add_pod(agent, cid, name):
    reply = agent.cni_server.add(CNIRequest(
        container_id=cid,
        extra_args={"K8S_POD_NAME": name, "K8S_POD_NAMESPACE": "default"},
    ))
    assert reply.result == 0, reply
    return reply.interfaces[0].ip_addresses[0].address.split("/")[0]


verdict = {"proc": PROC_ID, "local_nodes": runtime.cluster.local_nodes}
my_agent = runtime.agents[0]
pod_name = f"pod{runtime.cluster.local_nodes[0]}"
my_ip = add_pod(my_agent, f"cid-{pod_name}", pod_name)
store.put(f"/test/{pod_name}_ip", my_ip)
ip0 = wait_for(lambda: store.get("/test/pod0_ip"), "pod0 ip")
ip2 = wait_for(lambda: store.get("/test/pod2_ip"), "pod2 ip")
wait_for(lambda: runtime.driver.applied >= 1, "first epoch")

codec = PacketCodec(snap=SNAP)


def push_wire(sport):
    """One UDP wire frame pod0 -> pod2 into node0's rx ring (P0)."""
    scratch = np.zeros((SNAP, SNAP), np.uint8)
    lens = np.zeros(SNAP, np.uint32)
    f = make_frame(ip0, ip2, proto=17, sport=sport, dport=5000,
                   payload=b"vppt" + b"x" * 28)
    scratch[0, :len(f)] = np.frombuffer(f, np.uint8)
    lens[0] = len(f)
    if_a = my_agent.dataplane.pod_if[("default", "pod0")]
    cols, k = codec.parse_inplace(scratch, lens, 1, if_a)
    assert runtime.ring_pairs[0].rx.push(cols, k, payload=scratch)


def drain_tx_count(ip_dst):
    """P1: pop node 2's tx ring; count delivered frames to ip_dst with
    intact UDP payload bytes."""
    import ipaddress

    want = int(ipaddress.ip_address(ip_dst))
    got = 0
    while True:
        fr = runtime.ring_pairs[0].tx.peek()
        if fr is None:
            return got
        for s_ in range(fr.n):
            if (int(fr.cols["dst_ip"][s_]) == want
                    and fr.cols["disp"][s_] == int(Disposition.LOCAL)
                    and fr.cols["proto"][s_] == 17):
                # wire.py's UDP payload body survives the fabric
                assert bytes(fr.payload[s_, 42:46]) == b"vppt", \
                    bytes(fr.payload[s_, 40:60])
                got += 1
        runtime.ring_pairs[0].tx.release()


if PROC_ID == 0:
    seq = iter(range(20000, 29000))

    def delivered():
        # unique sport per push: a repeated 5-tuple would install a
        # reflective session that (correctly) outlives the later policy
        # and pollute the stage-2 verdict
        push_wire(next(seq))
        time.sleep(0.1)
        return int(store.get("/test/wire1_count") or 0) > 0

    wait_for(delivered, "wire delivery", 120)
    verdict["stage1_ok"] = True
    # the retry loop may have queued a backlog (pushes outpace the
    # 1-frame/ring/tick fleet-agreed drain) — let it fully flush before
    # the peer snapshots its pre-policy counters
    base = runtime.driver.ticks
    wait_for(lambda: runtime.driver.ticks > base + 24, "backlog drain")
    # fleet-idle window (the peer is blocked waiting on stage1_drained,
    # our backlog is flushed): ticks must advance WITHOUT device steps
    s0 = runtime.cluster_pump.stats["steps"]
    t0 = runtime.driver.ticks
    wait_for(lambda: runtime.driver.ticks > t0 + 8, "idle ticks")
    verdict["idle_steps_flat"] = \
        runtime.cluster_pump.stats["steps"] == s0
    store.put("/test/stage1_drained", True)
    # stage 2: serve fresh-sport waves on request until the peer is
    # done evaluating the policy cutoff
    sport = iter(range(30000, 60000))
    acked = 0
    deadline = time.monotonic() + 150
    while time.monotonic() < deadline:
        if store.get("/test/p1_done"):
            break
        req = int(store.get("/test/wave_req") or 0)
        if req > acked:
            base = runtime.driver.ticks
            for _ in range(4):
                push_wire(next(sport))
            wait_for(lambda: runtime.driver.ticks > base + 5,
                     "wave ticks", 60)
            acked = req
            store.put("/test/wave_ack", acked)
        else:
            time.sleep(0.1)
else:
    total = 0

    def got_wire():
        global total
        total += drain_tx_count(my_ip)
        if total:
            store.put("/test/wire1_count", total)
        return total

    wait_for(got_wire, "wire delivery at pod2", 120)
    verdict["wire_delivered"] = total
    wait_for(lambda: store.get("/test/stage1_drained"),
             "sender backlog drained", 120)

    # isolate pod2 through the agent's REAL policy machinery: a KSR
    # Pod (labels) + an ingress NetworkPolicy with no rules, written to
    # the shared store exactly as contiv-ksr would — the agent's watch
    # -> processor -> renderer path stages the deny and its commit
    # rides the lockstep epoch. (A test-owned TpuRenderer would race
    # the agent's own renderer over the global table.)
    from vpp_tpu.cmd.ksr_main import KsrAgent
    from vpp_tpu.ksr import model as m

    steps_before_commit = runtime.cluster_pump.stats["steps"]
    ksr = KsrAgent(store=store, serve_http=False)
    ksr.start()
    ksr.sources[m.Pod.TYPE].add("default/pod2", m.Pod(
        name="pod2", namespace="default",
        labels={"app": "pod2"}, ip_address=my_ip))
    ksr.sources[m.Policy.TYPE].add("default/iso", m.Policy(
        name="iso", namespace="default",
        pods=m.LabelSelector(match_labels={"app": "pod2"}),
        policy_type=m.POLICY_INGRESS, ingress_rules=[]))

    # a commit tick must STEP even on an idle fleet (session state
    # migrates onto the new epoch); observable as steps advancing while
    # no traffic flows
    applied0 = runtime.driver.applied
    wait_for(lambda: runtime.driver.applied > applied0,
             "policy epoch applied", 120)
    # steps counts in the WRITER thread after the item lands — wait,
    # don't snapshot-race it
    wait_for(lambda: runtime.cluster_pump.stats["steps"]
             > steps_before_commit, "commit-tick step", 60)
    verdict["commit_stepped"] = True

    # converge: waves of fresh-sport frames from P0 until one FULL wave
    # yields zero deliveries (policy propagation is async: watch ->
    # commit -> agreed publish)
    cut = False
    deadline = time.monotonic() + 120
    wave = 0
    while time.monotonic() < deadline and not cut:
        drain_tx_count(my_ip)              # discard anything in flight
        wave += 1
        store.put("/test/wave_req", wave)
        wait_for(lambda: int(store.get("/test/wave_ack") or 0) >= wave,
                 f"wave {wave} ack", 60)
        base = runtime.driver.ticks
        wait_for(lambda: runtime.driver.ticks > base + 6,
                 "wave settle", 60)
        cut = drain_tx_count(my_ip) == 0
    verdict["stage2_cut"] = cut
    store.put("/test/p1_done", True)

runtime.close()
print("VERDICT " + json.dumps(verdict), flush=True)
