"""Mesh-mode agent e2e: the DEPLOYED multi-chip data plane.

VERDICT r3 Missing #1 / Next #1: ClusterDataplane must be reachable
from the deployed agent stack, not only from tests. Here the full
control plane runs in mesh mode — N ContivAgents (KSR watch bridge,
policy/service plugins, renderers, CNI server, node events) driving
cluster node handles through the UNCHANGED commit paths — and traffic
crosses nodes through the all_to_all ICI fabric (reference analog:
two_node_two_pods.robot over the node_events.go VXLAN mesh).
"""

import numpy as np
import pytest

from vpp_tpu.cmd import AgentConfig
from vpp_tpu.cmd.ksr_main import KsrAgent
from vpp_tpu.cni.model import CNIRequest
from vpp_tpu.ksr import model as m
from vpp_tpu.kvstore.store import KVStore
from vpp_tpu.parallel.runtime import MeshRuntime
from vpp_tpu.pipeline.tables import DataplaneConfig
from vpp_tpu.pipeline.vector import Disposition


def boot_mesh(n_nodes=2, rule_shards=2):
    store = KVStore()
    ksr = KsrAgent(store=store, serve_http=False)
    ksr.start()
    cfg = AgentConfig(
        node_name="mesh",
        serve_http=False,
        dataplane=DataplaneConfig(
            max_tables=4, max_rules=16, max_global_rules=32, max_ifaces=16,
            fib_slots=64, sess_slots=256, nat_mappings=4, nat_backends=16,
        ),
    )
    runtime = MeshRuntime(n_nodes, cfg, rule_shards=rule_shards, store=store)
    runtime.start()
    return store, ksr, runtime


def add_pod(agent, cid, name, ns="default"):
    reply = agent.cni_server.add(CNIRequest(
        container_id=cid,
        extra_args={"K8S_POD_NAME": name, "K8S_POD_NAMESPACE": ns},
    ))
    assert reply.result == 0
    return reply.interfaces[0].ip_addresses[0].address.split("/")[0]


def reflect_pod(ksr, name, ip, labels, ns="default"):
    ksr.sources[m.Pod.TYPE].add(
        f"{ns}/{name}",
        m.Pod(name=name, namespace=ns, labels=labels, ip_address=ip),
    )


def cross_node_send(runtime, src_node, src_pod, src_ip, dst_ip, dport,
                    sport=41000, proto=6):
    """One cluster step carrying src_pod's packet; returns the delivery
    disposition observed at every node's pass-2 row + the full result."""
    agent = runtime.agents[src_node]
    frames = [[] for _ in range(runtime.n_nodes)]
    frames[src_node] = [{
        "src": src_ip, "dst": dst_ip, "proto": proto, "sport": sport,
        "dport": dport, "rx_if": agent.dataplane.pod_if[src_pod],
    }]
    res = runtime.step(runtime.make_frames(frames, n=8))
    return res


def test_mesh_two_node_fabric_path_and_policy_cutoff():
    """A pod on node 0 reaches a pod on node 1 THROUGH THE FABRIC
    (all_to_all delivery, not VXLAN), then a NetworkPolicy reflected via
    KSR cuts the flow at the destination node."""
    store, ksr, runtime = boot_mesh()
    a0, a1 = runtime.agents

    # Node registration flowed through the store: each agent installed
    # a FABRIC route (node_id = peer mesh row, next_hop 0) to its peer.
    assert runtime.mesh_position(a0.node_id) == 0
    assert runtime.mesh_position(a1.node_id) == 1
    b0 = a0.dataplane.builder
    fabric_rows = b0.fib_node_id[b0.fib_plen >= 0]
    assert 1 in fabric_rows, "node 0 has a fabric route to mesh row 1"
    assert (b0.fib_next_hop[(b0.fib_plen >= 0) & (b0.fib_node_id == 1)]
            == 0).all(), "fabric routes carry no VXLAN next_hop"

    ip_web = add_pod(a0, "c-web", "web")
    ip_db = add_pod(a1, "c-db", "db")
    reflect_pod(ksr, "web", ip_web, {"app": "web"})
    reflect_pod(ksr, "db", ip_db, {"app": "db"})
    ksr.sources[m.Namespace.TYPE].add(
        "default", m.Namespace(name="default", labels={})
    )

    # No policy: web (node 0) -> db (node 1) crosses the fabric and is
    # delivered to db's pod interface in pass 2 at node 1.
    res = cross_node_send(runtime, 0, ("default", "web"), ip_web, ip_db, 5432)
    local0 = np.asarray(res.local.disp)[0]
    assert local0[0] == int(Disposition.REMOTE)
    assert np.asarray(res.local.node_id)[0][0] == 1, "handed to fabric row 1"
    assert int(np.asarray(res.fabric_sent).sum()) == 1
    d_disp = np.asarray(res.delivered.disp)[1]
    d_txif = np.asarray(res.delivered.tx_if)[1]
    slots = np.nonzero(d_disp == int(Disposition.LOCAL))[0]
    assert len(slots) == 1, "delivered exactly once at node 1"
    assert d_txif[slots[0]] == a1.dataplane.pod_if[("default", "db")]

    # Ingress policy: db accepts only app=frontend on 8080 — web is cut.
    ksr.sources[m.Policy.TYPE].add("default/db-policy", m.Policy(
        name="db-policy", namespace="default",
        pods=m.LabelSelector(match_labels={"app": "db"}),
        policy_type=m.POLICY_INGRESS,
        ingress_rules=[m.PolicyRule(
            ports=[m.PolicyPort(protocol="TCP", port=8080)],
            peers=[m.PolicyPeer(
                pods=m.LabelSelector(match_labels={"app": "frontend"}))],
        )],
    ))
    res = cross_node_send(runtime, 0, ("default", "web"), ip_web, ip_db,
                          5432, sport=41001)
    d_disp = np.asarray(res.delivered.disp)[1]
    assert not np.any(d_disp == int(Disposition.LOCAL)), "policy cuts web->db"
    assert int(np.asarray(res.stats.drop_acl).sum()) >= 1

    # Policy removed: flow restored (and the fabric still carries it).
    ksr.sources[m.Policy.TYPE].delete("default/db-policy")
    res = cross_node_send(runtime, 0, ("default", "web"), ip_web, ip_db,
                          5432, sport=41002)
    d_disp = np.asarray(res.delivered.disp)[1]
    assert np.any(d_disp == int(Disposition.LOCAL))
    runtime.close()


def test_mesh_same_node_traffic_stays_off_fabric():
    store, ksr, runtime = boot_mesh()
    a0 = runtime.agents[0]
    ip_a = add_pod(a0, "c-a", "pa")
    ip_b = add_pod(a0, "c-b", "pb")
    res = cross_node_send(runtime, 0, ("default", "pa"), ip_a, ip_b, 80)
    local0 = np.asarray(res.local.disp)[0]
    assert local0[0] == int(Disposition.LOCAL)
    assert int(np.asarray(res.fabric_sent).sum()) == 0
    runtime.close()


def test_mesh_service_nat_across_nodes():
    """ClusterIP VIP resolved by node 0's NAT to a backend on node 1:
    DNAT at ingress, fabric delivery at the backend's node."""
    store, ksr, runtime = boot_mesh()
    a0, a1 = runtime.agents
    ip_cli = add_pod(a0, "c-cli", "client")
    ip_be = add_pod(a1, "c-be", "backend")

    ksr.sources[m.Service.TYPE].add("default/web", m.Service(
        name="web", namespace="default", cluster_ip="10.96.0.50",
        ports=[m.ServicePort(name="http", protocol="TCP", port=80,
                             target_port="http")],
    ))
    ksr.sources[m.Endpoints.TYPE].add("default/web", m.Endpoints(
        name="web", namespace="default",
        subsets=[m.EndpointSubset(
            addresses=[m.EndpointAddress(ip=ip_be,
                                         node_name=a1.config.node_name)],
            ports=[m.EndpointPort(name="http", port=8080, protocol="TCP")],
        )],
    ))

    res = cross_node_send(runtime, 0, ("default", "client"), ip_cli,
                          "10.96.0.50", 80)
    # DNAT happened at node 0 (ingress), then the rewritten packet rode
    # the fabric to the backend's node.
    assert int(np.asarray(res.stats.dnat)[0]) == 1
    d_disp = np.asarray(res.delivered.disp)[1]
    d_dport = np.asarray(res.delivered.pkts.dport)[1]
    slots = np.nonzero(d_disp == int(Disposition.LOCAL))[0]
    assert len(slots) == 1
    assert d_dport[slots[0]] == 8080, "VIP translated to target port"
    runtime.close()


@pytest.mark.slow  # ~30 s: compiles a second wire-step coalesce
# bucket on top of the fabric program — the coalesce semantics are
# also pinned by the single-node pump suite; tier-1 keeps the other
# mesh-agent e2e cases
def test_cluster_pump_coalesces_backlog():
    """A pre-staged backlog of rx frames is coalesced into FEWER fabric
    steps (the VEC*MAX_FRAMES bucket) than frames — and every packet
    still delivers at the peer with its bytes. The pump starts only
    AFTER the backlog is staged, so the coalesce assertion is
    deterministic."""
    import sys
    import time as _t

    import numpy as np

    sys.path.insert(0, "tests")
    from wire import make_frame

    from vpp_tpu.io.cluster_pump import MAX_FRAMES, ClusterPump
    from vpp_tpu.io.rings import IORingPair
    from vpp_tpu.native.pktio import PacketCodec

    store = KVStore()
    ksr = KsrAgent(store=store, serve_http=False)
    ksr.start()
    cfg = AgentConfig(
        node_name="clp", serve_http=False,
        dataplane=DataplaneConfig(
            max_tables=4, max_rules=16, max_global_rules=32, max_ifaces=16,
            fib_slots=64, sess_slots=256, nat_mappings=4, nat_backends=16,
        ),
    )
    runtime = MeshRuntime(2, cfg, rule_shards=2, store=store).start()
    rings = [IORingPair(n_slots=16, snap=256) for _ in range(2)]
    pump = ClusterPump(runtime.cluster, rings, snap=256)
    try:
        a0, a1 = runtime.agents
        ip_a = add_pod(a0, "c-a", "pa")
        ip_b = add_pod(a1, "c-b", "pb")
        if_a = a0.dataplane.pod_if[("default", "pa")]

        pump.warm()
        codec = PacketCodec(snap=256)
        scratch = np.zeros((256, 256), np.uint8)
        lens = np.zeros(256, np.uint32)
        n_frames, per = MAX_FRAMES, 8
        # stage the WHOLE backlog before the pump thread exists
        for j in range(n_frames):
            for i in range(per):
                f = make_frame(ip_a, ip_b, proto=17,
                               sport=30000 + j * per + i, dport=80)
                scratch[i, :len(f)] = np.frombuffer(f, np.uint8)
                lens[i] = len(f)
            cols, k = codec.parse_inplace(scratch, lens, per, if_a)
            assert rings[0].rx.push(cols, k, payload=scratch)
        pump.start()

        deadline = _t.monotonic() + 60
        while (_t.monotonic() < deadline
               and pump.stats["fabric_pkts"] < n_frames * per):
            _t.sleep(0.05)
        assert pump.stats["fabric_pkts"] == n_frames * per
        # the pre-staged backlog crossed in ONE coalesced step
        assert pump.stats["max_coalesce"] == n_frames
        assert pump.stats["steps"] == 1

        # drain node 1's tx ring: every packet delivered with bytes
        got = 0
        deadline = _t.monotonic() + 10
        while got < n_frames * per and _t.monotonic() < deadline:
            fr = rings[1].tx.peek()
            if fr is None:
                _t.sleep(0.02)
                continue
            live = (fr.cols["disp"][:fr.n]
                    == int(Disposition.LOCAL)).sum()
            got += int(live)
            # payload survived the fabric for the first packet
            assert fr.payload[0, 12:14].tobytes() == b"\x08\x00"
            rings[1].tx.release()
        assert got == n_frames * per
    finally:
        pump.stop(join_timeout=30.0)
        runtime.close()
        for r in rings:
            r.close()


def test_mesh_runtime_restart_keeps_identity(tmp_path):
    """A restarted mesh runtime (persisted local store, the
    connect_store path) reclaims the SAME allocator node ids and pod
    addresses — pods that survived the restart keep their IPs exactly
    like a standalone agent restart (kvstore-backed NodeIDAllocator +
    CNI resync)."""
    import dataclasses

    persist = str(tmp_path / "mesh-store.json")
    cfg = AgentConfig(
        node_name="rst", serve_http=False, persist_path=persist,
        dataplane=DataplaneConfig(
            max_tables=4, max_rules=16, max_global_rules=32, max_ifaces=16,
            fib_slots=64, sess_slots=256, nat_mappings=4, nat_backends=16,
        ),
    )
    rt1 = MeshRuntime(2, cfg).start()
    ids1 = [a.node_id for a in rt1.agents]
    ip1 = add_pod(rt1.agents[0], "c-keep", "keeper")
    rt1.agents[0].store.save()
    rt1.close()

    rt2 = MeshRuntime(2, dataclasses.replace(cfg)).start()
    try:
        assert [a.node_id for a in rt2.agents] == ids1, \
            "allocator ids must survive the restart"
        # the persisted pod resynced with its original address
        a0 = rt2.agents[0]
        assert ("default", "keeper") in a0.dataplane.pod_if
        assert str(a0.ipam.get_pod_ip("default/keeper")) == ip1
    finally:
        rt2.close()


def test_mesh_over_remote_kvserver():
    """Mesh agents against a REAL served kvstore (the deployed-etcd
    analog, in-process KVServer over TCP): node registration, KSR
    reflection and the fabric path all work through the remote store —
    the production store_url configuration of vpp-tpu-mesh-agent."""
    from vpp_tpu.kvstore.server import KVServer

    server = KVServer(host="127.0.0.1", port=0)
    server.start()
    try:
        url = f"tcp://127.0.0.1:{server.port}"
        from vpp_tpu.kvstore.client import connect_store

        ksr = KsrAgent(store=connect_store(url), serve_http=False)
        ksr.start()
        cfg = AgentConfig(
            node_name="rkv", serve_http=False, store_url=url,
            dataplane=DataplaneConfig(
                max_tables=4, max_rules=16, max_global_rules=32,
                max_ifaces=16, fib_slots=64, sess_slots=256,
                nat_mappings=4, nat_backends=16,
            ),
        )
        # no injected store: MeshRuntime connects via store_url itself
        runtime = MeshRuntime(2, cfg, rule_shards=2)
        runtime.start()
        try:
            a0, a1 = runtime.agents
            assert {runtime.mesh_position(a0.node_id),
                    runtime.mesh_position(a1.node_id)} == {0, 1}
            ip_a = add_pod(a0, "c-ra", "rpa")
            ip_b = add_pod(a1, "c-rb", "rpb")
            # policy reflected through the SERVED store cuts the flow
            ksr.sources[m.Pod.TYPE].add("default/rpa", m.Pod(
                name="rpa", namespace="default", labels={"app": "rpa"},
                ip_address=ip_a))
            ksr.sources[m.Pod.TYPE].add("default/rpb", m.Pod(
                name="rpb", namespace="default", labels={"app": "rpb"},
                ip_address=ip_b))
            res = cross_node_send(runtime, 0, ("default", "rpa"),
                                  ip_a, ip_b, 443)
            d_disp = np.asarray(res.delivered.disp)[1]
            assert np.any(d_disp == int(Disposition.LOCAL)), \
                "fabric delivery through the remote-store mesh"
            ksr.sources[m.Policy.TYPE].add("default/iso", m.Policy(
                name="iso", namespace="default",
                pods=m.LabelSelector(match_labels={"app": "rpb"}),
                policy_type=m.POLICY_INGRESS, ingress_rules=[]))
            import time as _t

            deadline = _t.monotonic() + 20
            cut = False
            while _t.monotonic() < deadline and not cut:
                res = cross_node_send(runtime, 0, ("default", "rpa"),
                                      ip_a, ip_b, 443, sport=41100)
                cut = not np.any(
                    np.asarray(res.delivered.disp)[1]
                    == int(Disposition.LOCAL)
                )
                if not cut:
                    _t.sleep(0.2)
            assert cut, "policy over the remote store cuts the flow"
        finally:
            runtime.close()
            runtime.store.close()
    finally:
        ksr.close()
        ksr.store.close()
        server.close()


@pytest.mark.slow  # ~44 s: ICMP error path compiles its own wire-step variants; fabric path + policy stays fast below
def test_icmp_error_returns_across_the_fabric():
    """Traceroute hop 2, mesh edition: a TTL=2 packet from a pod on
    node 0 survives the ingress vswitch, crosses the fabric, and
    expires at NODE 1's pass — whose time-exceeded (src = node 1's pod
    gateway) is re-injected through the pipeline and rides the fabric
    BACK to the sender's node. No VXLAN, no kernel hops: the error
    path is the same all_to_all the data path uses."""
    import sys
    import time as _t

    sys.path.insert(0, "tests")
    from wire import make_frame

    from vpp_tpu.cmd.config import IOConfig
    from vpp_tpu.native.pktio import PacketCodec

    store = KVStore()
    ksr = KsrAgent(store=store, serve_http=False)
    ksr.start()
    cfg = AgentConfig(
        node_name="micmp", serve_http=False,
        dataplane=DataplaneConfig(
            max_tables=4, max_rules=16, max_global_rules=32, max_ifaces=16,
            fib_slots=64, sess_slots=256, nat_mappings=4, nat_backends=16,
        ),
        io=IOConfig(enabled=True, n_slots=16, snap=256),
    )
    runtime = MeshRuntime(2, cfg, rule_shards=2, store=store).start()
    try:
        a0, a1 = runtime.agents
        ip_a = add_pod(a0, "c-ia", "ipa")
        ip_b = add_pod(a1, "c-ib", "ipb")
        gw1 = str(a1.ipam.pod_gateway_ip())
        if_a = a0.dataplane.pod_if[("default", "ipa")]

        codec = PacketCodec(snap=256)
        scratch = np.zeros((256, 256), np.uint8)
        lens = np.zeros(256, np.uint32)
        probe = make_frame(ip_a, ip_b, proto=17, sport=33434,
                           dport=33434, ttl=2)
        scratch[0, :len(probe)] = np.frombuffer(probe, np.uint8)
        lens[0] = len(probe)
        cols, k = codec.parse_inplace(scratch, lens, 1, if_a)
        assert runtime.ring_pairs[0].rx.push(cols, k, payload=scratch)

        from vpp_tpu.pipeline.vector import ip4

        deadline = _t.monotonic() + 60
        hop = None
        while _t.monotonic() < deadline and hop is None:
            fr = runtime.ring_pairs[0].tx.peek()
            if fr is None:
                _t.sleep(0.05)
                continue
            for s_ in range(fr.n):
                if (fr.cols["proto"][s_] == 1
                        and fr.cols["disp"][s_]
                        == int(Disposition.LOCAL)):
                    hop = (int(fr.cols["src_ip"][s_]),
                           int(fr.cols["dst_ip"][s_]),
                           bytes(fr.payload[s_, 34:36]))
                    break
            runtime.ring_pairs[0].tx.release()
        assert hop is not None, "no ICMP error returned across the fabric"
        src, dst, icmp_hdr = hop
        assert src == int(ip4(gw1)), \
            "time-exceeded originates from the REMOTE node's gateway"
        assert dst == int(ip4(ip_a))
        assert icmp_hdr[0] == 11 and icmp_hdr[1] == 0
        assert runtime.cluster_pump.stats.get("icmp_errors", 0) >= 1
    finally:
        runtime.close()


def test_cluster_session_aging_reclaims_slots():
    """Mesh-mode session aging: the cluster-level expire_sessions (the
    MeshRuntime maintenance loop's call) reclaims idle sessions across
    the node-stacked tables in bulk."""
    store, ksr, runtime = boot_mesh()
    try:
        a0, a1 = runtime.agents
        ip_a = add_pod(a0, "c-sa", "sa")
        ip_b = add_pod(a1, "c-sb", "sb")
        cross_node_send(runtime, 0, ("default", "sa"), ip_a, ip_b, 443)
        live = int(np.asarray(runtime.cluster.tables.sess_valid).sum())
        assert live >= 1
        # simulate idle time past the timeout, then bulk-reclaim
        from vpp_tpu.pipeline.dataplane import Dataplane

        runtime.cluster.advance_clock(
            (runtime.cluster.config.sess_max_age + 10)
            / Dataplane.TICKS_PER_SEC
        )
        expired = runtime.cluster.expire_sessions()
        assert expired == live
        assert int(
            np.asarray(runtime.cluster.tables.sess_valid).sum()
        ) == 0
    finally:
        runtime.close()


def test_mesh_per_node_vcl_sockets(tmp_path):
    """vcl_socket in mesh mode: every node agent serves ITS OWN
    admission socket (suffixed per node, _node_config) backed by its
    own SessionRuleEngine — a shared path would cross namespaces."""
    import socket as pysocket
    import struct as pystruct

    from vpp_tpu.hoststack.admission import OP_CONNECT, _REQ
    from vpp_tpu.hoststack.session_rules import (
        RuleAction, RuleScope, SessionRule,
    )

    store = KVStore()
    base = str(tmp_path / "vcl.sock")
    cfg = AgentConfig(
        node_name="mv", serve_http=False, vcl_socket=base,
        dataplane=DataplaneConfig(
            max_tables=4, max_rules=16, max_global_rules=32,
            max_ifaces=16, fib_slots=64, sess_slots=256,
            nat_mappings=4, nat_backends=16,
        ),
    )
    runtime = MeshRuntime(2, cfg, rule_shards=2, store=store)
    runtime.start()
    try:
        # node 1's engine denies appns 4 -> *:9100; node 0's allows
        runtime.agents[1].session_engine.apply(add=[SessionRule(
            scope=int(RuleScope.LOCAL), appns_index=4,
            transport_proto=6, lcl_net=0, lcl_plen=0, rmt_net=0,
            rmt_plen=0, lcl_port=0, rmt_port=9100,
            action=int(RuleAction.DENY))])

        def ask(node: int) -> bytes:
            s = pysocket.socket(pysocket.AF_UNIX, pysocket.SOCK_STREAM)
            s.connect(f"{base}.{node}")
            s.sendall(_REQ.pack(OP_CONNECT, 6, 0, 4, 0,
                                pystruct.unpack(
                                    "!I", pysocket.inet_aton(
                                        "127.0.0.1"))[0], 0, 9100))
            out = s.recv(1)
            s.close()
            return out

        assert ask(0) == b"\x01"   # node 0: no such rule -> allow
        assert ask(1) == b"\x00"   # node 1: denied by ITS engine
    finally:
        runtime.close()
