"""VXLAN encap/decap: SoA kernel roundtrip + byte-level wire codec.

Reference semantics: vxlan full-mesh overlay between nodes (reference
plugins/contiv/node_events.go:184-250); VPP vxlan-input validates UDP
4789 + VNI, vxlan-encap sets outer TTL 254 and RFC 7348 source-port
entropy.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from vpp_tpu.ops.vxlan import (
    DEFAULT_VNI,
    ENCAP_OVERHEAD,
    OUTER_TTL,
    VXLAN_PORT,
    decode_frame,
    encode_frame,
    vxlan_decap,
    vxlan_encap,
)
from vpp_tpu.pipeline.dataplane import Dataplane
from vpp_tpu.pipeline.tables import DataplaneConfig
from vpp_tpu.pipeline.vector import Disposition, ip4, make_packet_vector

VTEP_A = ip4("192.168.16.1")
VTEP_B = ip4("192.168.16.2")


def sample_inner(n=8):
    return make_packet_vector(
        [
            dict(src="10.1.1.2", dst="10.2.1.3", proto=6, sport=40000 + i,
                 dport=80, len=120, rx_if=1)
            for i in range(n)
        ]
    )


def test_encap_sets_outer_headers():
    inner = sample_inner()
    mask = inner.valid
    outer = vxlan_encap(inner, mask, jnp.uint32(VTEP_A),
                        jnp.full_like(inner.dst_ip, VTEP_B))
    v = np.asarray(outer.valid)
    assert v[:8].all() and not v[8:].any()
    assert (np.asarray(outer.src_ip)[:8] == VTEP_A).all()
    assert (np.asarray(outer.dst_ip)[:8] == VTEP_B).all()
    assert (np.asarray(outer.proto)[:8] == 17).all()
    assert (np.asarray(outer.dport)[:8] == VXLAN_PORT).all()
    assert (np.asarray(outer.ttl)[:8] == OUTER_TTL).all()
    assert (np.asarray(outer.pkt_len)[:8] == 120 + ENCAP_OVERHEAD).all()


def test_encap_sport_entropy_stable_per_flow():
    inner = sample_inner()
    outer1 = vxlan_encap(inner, inner.valid, jnp.uint32(VTEP_A),
                         jnp.full_like(inner.dst_ip, VTEP_B))
    outer2 = vxlan_encap(inner, inner.valid, jnp.uint32(VTEP_A),
                         jnp.full_like(inner.dst_ip, VTEP_B))
    s1, s2 = np.asarray(outer1.sport), np.asarray(outer2.sport)
    assert (s1 == s2).all(), "per-flow sport must be deterministic"
    assert ((s1[:8] >= 49152) & (s1[:8] <= 65535)).all()
    # different flows should spread (at least not all collide)
    assert len(set(s1[:8].tolist())) > 1


def test_decap_roundtrip_and_vni_check():
    inner = sample_inner()
    outer = vxlan_encap(inner, inner.valid, jnp.uint32(VTEP_A),
                        jnp.full_like(inner.dst_ip, VTEP_B))
    vni = jnp.full(inner.src_ip.shape, DEFAULT_VNI, jnp.int32)
    res = vxlan_decap(outer, inner, vni, local_vtep=jnp.uint32(VTEP_B))
    assert np.asarray(res.ok)[:8].all()
    assert (np.asarray(res.inner.dst_ip)[:8] == ip4("10.2.1.3")).all()

    # wrong VNI → rejected
    res_bad = vxlan_decap(outer, inner, vni + 1, local_vtep=jnp.uint32(VTEP_B))
    assert not np.asarray(res_bad.ok).any()
    assert not np.asarray(res_bad.inner.valid).any()

    # outer not addressed to us → rejected
    res_notus = vxlan_decap(outer, inner, vni, local_vtep=jnp.uint32(VTEP_A))
    assert not np.asarray(res_notus.ok).any()


def test_dataplane_encap_remote_path():
    dp = Dataplane(DataplaneConfig())
    uplink = dp.add_uplink()
    pod = dp.add_pod_interface(("default", "a"))
    dp.builder.add_route("10.1.1.0/24", pod, Disposition.LOCAL)
    dp.builder.add_route(
        "10.2.0.0/16", uplink, Disposition.REMOTE,
        next_hop=VTEP_B, node_id=2,
    )
    dp.swap()
    dp.set_vtep(VTEP_A)
    pkts = make_packet_vector(
        [dict(src="10.1.1.5", dst="10.2.3.4", proto=17, sport=1000,
              dport=53, rx_if=pod)]
    )
    res = dp.process(pkts)
    assert int(res.node_id[0]) == 2
    outer = dp.encap_remote(res)
    assert bool(outer.valid[0])
    assert int(outer.dst_ip[0]) == VTEP_B
    assert int(outer.src_ip[0]) == VTEP_A
    # Edge peers without a fabric node index (node_id=-1, the default)
    # are REMOTE-disposed too and must still encap.
    dp.builder.add_route(
        "10.3.0.0/16", uplink, Disposition.REMOTE,
        next_hop=ip4("192.168.16.99"),
    )
    dp.swap()
    res_edge = dp.process(make_packet_vector(
        [dict(src="10.1.1.5", dst="10.3.1.1", proto=17, sport=7,
              dport=53, rx_if=pod)]
    ))
    assert int(res_edge.node_id[0]) == -1
    outer_edge = dp.encap_remote(res_edge)
    assert bool(outer_edge.valid[0])
    assert int(outer_edge.dst_ip[0]) == ip4("192.168.16.99")
    # local packets never encap
    pkts_local = make_packet_vector(
        [dict(src="10.1.1.5", dst="10.1.1.6", proto=17, sport=1,
              dport=2, rx_if=pod)]
    )
    res2 = dp.process(pkts_local)
    outer2 = dp.encap_remote(res2)
    assert not np.asarray(outer2.valid).any()


def test_wire_codec_roundtrip():
    outer = {"src": VTEP_A, "dst": VTEP_B, "sport": 50000, "ttl": OUTER_TTL}
    inner = {"src": ip4("10.1.1.2"), "dst": ip4("10.2.1.3"), "proto": 17,
             "ttl": 63, "sport": 1234, "dport": 53}
    wire = encode_frame(outer, inner, vni=42, inner_payload=b"hello")
    o, i, vni, payload = decode_frame(wire)
    assert vni == 42
    assert o["src"] == VTEP_A and o["dst"] == VTEP_B
    assert o["dport"] == VXLAN_PORT
    assert i["src"] == ip4("10.1.1.2") and i["dst"] == ip4("10.2.1.3")
    assert i["sport"] == 1234 and i["dport"] == 53
    assert payload == b"hello"


def test_wire_codec_rejects_non_vxlan():
    outer = {"src": VTEP_A, "dst": VTEP_B}
    inner = {"src": 1, "dst": 2, "proto": 6, "sport": 1, "dport": 2}
    good = encode_frame(outer, inner)

    wire = bytearray(good)
    wire[22] = 0x01  # corrupt UDP dst port
    wire[23] = 0x02
    with pytest.raises(ValueError):
        decode_frame(bytes(wire))

    # non-UDP outer (e.g. GRE) must be rejected even if payload bytes
    # happen to look like port 4789
    wire = bytearray(good)
    wire[9] = 47  # outer proto = GRE
    with pytest.raises(ValueError):
        decode_frame(bytes(wire))

    # outer with IP options (IHL > 5) shifts offsets — rejected
    wire = bytearray(good)
    wire[0] = 0x46
    with pytest.raises(ValueError):
        decode_frame(bytes(wire))

    # truncated frame raises ValueError, not struct.error
    with pytest.raises(ValueError):
        decode_frame(good[:40])

    # non-IPv4 inner ethertype rejected
    wire = bytearray(good)
    wire[48] = 0x86  # ethertype -> 0x86DD (IPv6)
    wire[49] = 0xDD
    with pytest.raises(ValueError):
        decode_frame(bytes(wire))
