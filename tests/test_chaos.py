"""Seeded chaos schedules (ISSUE 8: vpp_tpu/testing/faults.py).

Every schedule runs REAL components through their REAL failure seams
(compiled-in fault points, server kills, socket shutdowns) on a seeded,
reproducible plan, and after every recovery asserts an EXACT
conservation invariant — packets, sessions, or acknowledged writes —
never a vibes-level "it seems to work again":

* ``kvstore partition``     — server killed mid-write-stream + seeded
  RPC drops: every acknowledged put survives, the client reports
  degraded + staleness while down, and heals on restart.
* ``ring fault → dispatch`` — the resident device ring dies repeatedly:
  the pump falls back to the dispatch ladder, and
  delivered + attributed drops == offered, exactly.
* ``torn snapshot``         — a seeded schedule of torn chunks / torn
  manifests across generations: restore always yields the last
  PUBLISHED generation, bit-consistent, never a half-restored table.
* ``reconnect storm``       — seeded connect-failure storms around
  forced disconnects: watches re-register snapshot-atomically every
  round and no acknowledged write is lost.
* ``ml model refusals``     — a seeded schedule of corrupt artifacts +
  injected ``ml.load`` faults across model generations: every refusal
  is a counted outcome, the previous model KEEPS SERVING (verdicts
  unchanged, version never half-applied), degraded{component=ml}
  flips exactly while refused, and a good artifact heals.

Runtime is bounded (small tables, short timeouts). `make chaos` runs
the suite; the tests are also ``slow``-marked, so the tier-1
``-m 'not slow'`` run DESELECTS them — run `make chaos` explicitly
before merging resilience changes. Override the seed base with
VPPT_CHAOS_SEED to soak different schedules.
"""

from __future__ import annotations

import os
import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

from wire import make_frame

from vpp_tpu.io import DataplanePump, IORingPair
from vpp_tpu.kvstore.client import RemoteKVStore
from vpp_tpu.kvstore.server import KVServer
from vpp_tpu.kvstore.store import KVStore
from vpp_tpu.native.pktio import PacketCodec
from vpp_tpu.pipeline.dataplane import Dataplane
from vpp_tpu.pipeline.snapshot import SessionSnapshotter
from vpp_tpu.pipeline.tables import DataplaneConfig
from vpp_tpu.pipeline.vector import VEC, Disposition, make_packet_vector
from vpp_tpu.testing import faults

# `slow` keeps the seeded schedules out of the tier-1 `-m 'not slow'`
# timing budget (ISSUE 8 satellite); `make chaos` selects them by the
# chaos marker explicitly
pytestmark = [pytest.mark.chaos, pytest.mark.slow]

SEED = int(os.environ.get("VPPT_CHAOS_SEED", "0"))

CLIENT_IP = "10.1.1.2"
SERVER_IP = "10.1.1.3"


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    faults.uninstall()


def wait_for(pred, timeout=20.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.01)
    raise AssertionError(f"timed out waiting for {msg}")


# --------------------------------------------------------------------
# schedule 1: kvstore partition under a write stream
# --------------------------------------------------------------------


class TestKvstorePartition:
    def test_partition_conserves_acknowledged_writes(self):
        rng = np.random.default_rng(SEED + 1)
        shared = KVStore()
        srv = KVServer(store=shared, port=0)
        srv.start()
        port = srv.port
        client = RemoteKVStore("127.0.0.1", port, request_timeout=2.0,
                               reconnect_timeout=60.0)
        resyncs = []
        events = []
        client.watch("c/", events.append,
                     on_resync=lambda snap, rev: resyncs.append(rev))
        wait_for(lambda: len(resyncs) >= 1, msg="initial resync")
        try:
            # seeded RPC drops while healthy: the request layer must
            # absorb them transparently (retry within the deadline)
            drop_after = int(rng.integers(2, 5))
            plan = faults.install(faults.FaultPlan(seed=SEED + 1))
            plan.inject("kv.send", after=drop_after, times=2,
                        exc=OSError)
            acked = {}
            for i in range(8):
                client.put(f"c/k{i}", i)
                acked[f"c/k{i}"] = i
            assert plan.fired("kv.send") == 2
            faults.uninstall()

            # hard partition mid-stream: kill the server. In-flight /
            # subsequent puts may fail — ONLY acknowledged ones count.
            srv.close()
            wait_for(lambda: client.degraded, msg="degraded flag")
            t0 = client.staleness_s()
            assert t0 >= 0.0
            failures = 0
            for i in range(8, 12):
                try:
                    client.put(f"c/k{i}", i)
                    acked[f"c/k{i}"] = i
                except Exception:  # noqa: BLE001 — the partition
                    failures += 1
            assert failures > 0  # the partition was real
            assert client.staleness_s() >= t0

            # heal: same store, same port — the reconnect loop finds
            # it, re-registers the watch snapshot-atomically, and the
            # write path resumes
            srv2 = KVServer(store=shared, port=port)
            srv2.start()
            try:
                wait_for(lambda: not client.degraded,
                         msg="reconnect after heal")
                assert client.staleness_s() == 0.0
                wait_for(lambda: len(resyncs) >= 2,
                         msg="post-heal watch resync")
                for i in range(8, 12):  # retry the window, idempotent
                    client.put(f"c/k{i}", i)
                    acked[f"c/k{i}"] = i
                # EXACT conservation: every acknowledged write is in
                # the store, with the acknowledged value
                for k, v in acked.items():
                    assert shared.get(k) == v, k
                assert set(shared.list_keys("c/")) == set(acked)
            finally:
                srv2.close()
        finally:
            client.close()

    def test_agent_serves_last_epoch_and_exports_staleness(self):
        """The degraded-mode contract: with the store gone, already-
        adopted state keeps serving and the collector exports the
        kvstore degradation + staleness."""
        from vpp_tpu.stats.collector import StatsCollector

        srv = KVServer(store=KVStore(), port=0)
        srv.start()
        client = RemoteKVStore("127.0.0.1", srv.port,
                               request_timeout=1.0,
                               reconnect_timeout=5.0)
        dp = Dataplane(DataplaneConfig(sess_slots=64,
                                       sess_sweep_stride=0))
        a = dp.add_pod_interface(("default", "a"))
        b = dp.add_pod_interface(("default", "b"))
        dp.builder.add_route(f"{CLIENT_IP}/32", a, Disposition.LOCAL)
        dp.builder.add_route(f"{SERVER_IP}/32", b, Disposition.LOCAL)
        dp.swap()
        coll = StatsCollector(dp)
        coll.set_store(client)
        try:
            srv.close()
            wait_for(lambda: client.degraded, msg="degraded")
            # the data plane keeps forwarding on its adopted epoch
            pv = make_packet_vector(
                [{"src": CLIENT_IP, "dst": SERVER_IP, "proto": 17,
                  "sport": 1000, "dport": 53, "rx_if": a, "ttl": 64}],
                n=64)
            res = dp.process(pv, now=5)
            assert int(np.asarray(res.disp)[0]) == int(Disposition.LOCAL)
            coll.publish()
            lines = []
            for _p, fam in coll.registry.families():
                lines.extend(fam.render())
            text = "\n".join(lines)
            assert 'vpp_tpu_degraded{component="kvstore"} 1' in text
            stale = [ln for ln in text.splitlines()
                     if ln.startswith("vpp_tpu_kvstore_staleness_seconds")]
            assert stale and float(stale[0].split()[-1]) >= 0.0
        finally:
            client.close()


# --------------------------------------------------------------------
# schedule 2: resident-ring faults → dispatch-mode fallback
# --------------------------------------------------------------------


def _forwarding_dp():
    dp = Dataplane(DataplaneConfig(sess_slots=256, sess_sweep_stride=0))
    a = dp.add_pod_interface(("default", "a"))
    b = dp.add_pod_interface(("default", "b"))
    dp.builder.add_route(f"{CLIENT_IP}/32", a, Disposition.LOCAL)
    dp.builder.add_route(f"{SERVER_IP}/32", b, Disposition.LOCAL)
    dp.swap()
    return dp, a, b


def _push_frames(rings, rx_if, n_frames, per=4, tag0=20000):
    codec = PacketCodec()
    scratch = np.zeros((VEC, rings.rx.snap), np.uint8)
    pkts = 0
    for k in range(n_frames):
        frames = [
            make_frame(CLIENT_IP, SERVER_IP, proto=17,
                       sport=tag0 + k, dport=1000 + k * per + j)
            for j in range(per)
        ]
        cols, n = codec.parse(frames, rx_if, scratch)
        assert rings.rx.push(cols, n, payload=scratch)
        pkts += n
    return pkts


class TestRingFaultFallback:
    def test_repeated_ring_faults_fall_back_with_exact_conservation(
            self):
        dp, a, b = _forwarding_dp()
        rings = IORingPair(n_slots=32)
        # every window dispatch dies → two deaths trip the limit
        faults.install(faults.FaultPlan(seed=SEED + 2)).inject(
            "ring.dispatch", times=-1)
        pump = DataplanePump(dp, rings, mode="persistent",
                             ring_fault_limit=2).start()
        def accounted():
            s = pump.stats
            return (s["pkts"] + s["drops_error"] + s["drops_shutdown"]
                    + s["drops_tx_stall"] + s["drops_rx_full"])

        try:
            # keep offering traffic until the fault ladder trips: a
            # relaunched ring only dies when the NEXT frame reaches
            # its stager, so a single up-front burst would leave a
            # freshly relaunched (empty) ring looking healthy forever
            offered = 0
            deadline = time.monotonic() + 120.0
            k = 0
            while not pump.degraded_ring:
                assert time.monotonic() < deadline, \
                    "timed out waiting for ring→dispatch fallback"
                offered += _push_frames(rings, a, 2, per=4,
                                        tag0=20000 + 2 * k)
                k += 1
                time.sleep(0.3)
            assert pump.mode == "dispatch"
            # the degraded pump still moves traffic (the whole point);
            # the tx ring (32 slots) holds everything, so conservation
            # is read off the pump counters without a racing drain
            offered += _push_frames(rings, a, 6, per=4, tag0=30000)
            wait_for(lambda: accounted() == offered, timeout=180.0,
                     msg="every offered packet accounted")
            assert pump.stop(join_timeout=60.0)
            s = pump.stats
            # EXACT packet conservation across the mode switch: every
            # offered packet is either delivered or attributed to a
            # drop cause — the fallback itself loses nothing silently
            assert accounted() == offered, dict(s)
            assert s["pkts"] > 0  # post-fallback delivery happened
            # the fault really drove the fallback
            assert faults.active_plan().fired("ring.dispatch") >= 2
            assert s["batch_errors"] >= 1
        finally:
            pump.stop(join_timeout=30.0)
            rings.close()

    def test_healthy_ring_unaffected_by_armed_other_points(self):
        """Control: a plan arming only kvstore points leaves the ring
        path untouched (fault points are independent seams)."""
        dp, a, b = _forwarding_dp()
        rings = IORingPair(n_slots=32)
        faults.install(faults.FaultPlan(seed=SEED + 2)).inject(
            "kv.send", times=-1, exc=OSError)
        pump = DataplanePump(dp, rings, mode="persistent",
                             ring_fault_limit=2).start()
        try:
            offered = _push_frames(rings, a, 4, per=4)
            deadline = time.monotonic() + 120.0
            delivered = 0
            while delivered < offered and time.monotonic() < deadline:
                f = rings.tx.peek()
                if f is None:
                    time.sleep(0.005)
                    continue
                delivered += f.n
                rings.tx.release()
            assert delivered == offered
            assert not pump.degraded_ring
            assert pump.mode == "persistent"
        finally:
            pump.stop(join_timeout=60.0)
            rings.close()


class TestDispatchPathFaults:
    def test_fetch_and_tx_faults_attribute_drops_exactly(self):
        """Dispatch-mode seams: seeded result-fetch failures and a
        tx-ring stall. Loss is allowed — UNATTRIBUTED loss is not:
        delivered + drops_error + drops_tx_stall (+ the rest) must
        equal offered exactly, and traffic keeps flowing after."""
        dp, a, b = _forwarding_dp()
        rings = IORingPair(n_slots=32)
        pump = DataplanePump(dp, rings, mode="dispatch").start()

        def accounted():
            s = pump.stats
            return (s["pkts"] + s["drops_error"] + s["drops_shutdown"]
                    + s["drops_tx_stall"] + s["drops_rx_full"])

        try:
            # warm the dispatch path FIRST: the initial jit compile
            # takes tens of seconds, during which every pushed frame
            # coalesces into one batch — the armed per-call windows
            # below need distinct dispatches to land on
            offered = _push_frames(rings, a, 1, per=4, tag0=20999)
            wait_for(lambda: pump.stats["pkts"] >= 4, timeout=180.0,
                     msg="warm dispatch")
            plan = faults.install(faults.FaultPlan(seed=SEED + 5))
            plan.inject("pump.fetch", after=1, times=2)
            plan.inject("pump.tx_push", after=0, times=1)
            for k in range(6):  # spaced → distinct dispatches, so the
                # armed call windows land on different batches
                offered += _push_frames(rings, a, 1, per=4,
                                        tag0=21000 + k)
                time.sleep(0.25)
            wait_for(lambda: accounted() == offered, timeout=180.0,
                     msg="every offered packet accounted")
            assert pump.stop(join_timeout=60.0)
            s = pump.stats
            assert accounted() == offered, dict(s)
            assert s["drops_error"] > 0        # the fetch faults bit
            assert s["drops_tx_stall"] > 0     # the tx stall bit
            assert s["pkts"] > 0               # and traffic survived
            assert plan.fired("pump.fetch") == 2
            assert plan.fired("pump.tx_push") == 1
        finally:
            pump.stop(join_timeout=30.0)
            rings.close()


# --------------------------------------------------------------------
# schedule 3: torn-snapshot generations
# --------------------------------------------------------------------


class TestTornSnapshotSchedule:
    def test_seeded_torn_generations_always_restore_published_state(
            self, tmp_path):
        """Across a seeded schedule of OK / torn-chunk / torn-manifest
        snapshot attempts, restore must always produce exactly the
        last PUBLISHED generation's session set — never a blend."""
        rng = np.random.default_rng(SEED + 3)
        cfg = DataplaneConfig(
            max_ifaces=8, fib_slots=16, sess_slots=256, sess_ways=4,
            sess_sweep_stride=0)
        dp = Dataplane(cfg)
        up = dp.add_uplink()
        dp.builder.add_route("10.50.0.0/16", up, Disposition.LOCAL)
        dp.swap()
        snap = SessionSnapshotter(dp, str(tmp_path), chunk_buckets=16)

        published_live = 0
        total_flows = 0
        schedule = ["ok"] + [
            ["ok", "torn_chunk", "torn_manifest"][int(rng.integers(3))]
            for _ in range(5)
        ]
        for step, kind in enumerate(schedule):
            # fresh flows before every attempt (so every generation
            # has new content to drain)
            n = int(rng.integers(3, 9))
            pv = make_packet_vector(
                [{"src": f"172.20.{step}.{i + 1}",
                  "dst": f"10.50.{step}.{i + 1}", "proto": 6,
                  "sport": 5000 + i, "dport": 443, "rx_if": up,
                  "ttl": 64} for i in range(n)], n=64)
            dp.process(pv, now=10 + step)
            total_flows += n
            live_now = int(jnp.sum(dp.tables.sess_valid))
            if kind == "ok":
                assert snap.snapshot() is not None
                published_live = live_now
            else:
                point = ("snapshot.chunk" if kind == "torn_chunk"
                         else "snapshot.manifest")
                faults.install(
                    faults.FaultPlan(seed=SEED + 10 + step)).inject(point)
                assert snap.snapshot() is None
                faults.uninstall()
                assert snap.degraded

            # recovery check after EVERY attempt: a fresh process
            # restores exactly the last published generation
            dp2 = Dataplane(cfg)
            dp2.add_uplink()
            dp2.swap()
            snap2 = SessionSnapshotter(dp2, str(tmp_path),
                                       chunk_buckets=16)
            assert snap2.restore_into()
            restored = int(jnp.sum(dp2.tables.sess_valid))
            assert restored == published_live, (step, kind, schedule)

        # the schedule must actually have exercised a failure path
        # (seeded draw over 5 steps; P(all ok) < 2%) — and a final
        # clean snapshot heals regardless of history
        assert snap.snapshot() is not None
        assert not snap.degraded


# --------------------------------------------------------------------
# schedule 4: reconnect storm with watch re-registration
# --------------------------------------------------------------------


class TestReconnectStorm:
    def test_seeded_storm_conserves_writes_and_watch_state(self):
        import socket as _socket

        rng = np.random.default_rng(SEED + 4)
        shared = KVStore()
        srv = KVServer(store=shared, port=0)
        srv.start()
        client = RemoteKVStore("127.0.0.1", srv.port,
                               request_timeout=2.0,
                               reconnect_timeout=30.0,
                               reconnect_backoff=(0.02, 0.2))
        got = []
        got_lock = threading.Lock()
        resyncs = []

        def on_event(ev):
            with got_lock:
                got.append(ev.key)

        client.watch("s/", on_event,
                     on_resync=lambda snap, rev: resyncs.append(len(snap)))
        try:
            acked = {}
            rounds = 4
            for r in range(rounds):
                # seeded connect-failure burst for the upcoming
                # reconnect: the jittered backoff must ride through it
                k = int(rng.integers(1, 4))
                plan = faults.install(
                    faults.FaultPlan(seed=SEED + 40 + r))
                plan.inject("kv.connect", times=k, exc=OSError)
                # force the disconnect (the storm's trigger)
                with client._lock:
                    sock = client._sock
                assert sock is not None
                sock.shutdown(_socket.SHUT_RDWR)
                wait_for(lambda: len(resyncs) >= r + 2, timeout=30.0,
                         msg=f"resync after storm round {r}")
                assert plan.fired("kv.connect") == k
                faults.uninstall()
                key = f"s/round{r}"
                client.put(key, r)
                acked[key] = r
                wait_for(lambda: key in got, timeout=10.0,
                         msg=f"watch delivery round {r}")

            # conservation: every acknowledged write present, every
            # round's event delivered, one snapshot-atomic resync per
            # storm round plus the initial registration
            for k_, v in acked.items():
                assert shared.get(k_) == v
            assert set(shared.list_keys("s/")) == set(acked)
            assert len(resyncs) >= rounds + 1
        finally:
            client.close()
            srv.close()


# --------------------------------------------------------------------
# schedule 5: ML model load refusals across generations (ISSUE 10)
# --------------------------------------------------------------------


class TestMlModelRefusals:
    def test_refusal_schedule_keeps_previous_generation_serving(
            self, tmp_path):
        """Seeded schedule over the REAL ``ml.load`` seam
        (vpp_tpu/ml/loader.py): good v1 → injected load faults →
        corrupt file → good v2. Conservation after every round: the
        version the dataplane scores with is EXACTLY the last
        successfully published generation (never absent, never a
        half-applied blob — the w1 plane and the version scalar always
        belong to the same artifact), and the refusal ledger accounts
        for every attempt: loaded + refused == polls that found a
        changed file."""
        import numpy as np

        from vpp_tpu.ml.loader import MlModelSource
        from vpp_tpu.ml.model import MlModel, save_model
        from vpp_tpu.ops.mlscore import ML_FEATURES

        rng = np.random.default_rng(SEED + 60)

        def gen_model(version):
            # version-keyed weights so "which generation is serving"
            # is readable off the staged planes, not just the scalar
            w1 = np.zeros((ML_FEATURES, 4), np.int8)
            w1[12, 0] = np.int8(version)
            return MlModel(
                kind="mlp", version=version, n_features=ML_FEATURES,
                w1=w1, b1=np.zeros(4, np.int32), s1=0,
                w2=np.array([1, 0, 0, 0], np.int8), b2=0,
                flag_thresh=10, action="drop").validate()

        dp = Dataplane(DataplaneConfig(
            max_tables=2, max_rules=8, max_global_rules=8,
            max_ifaces=8, fib_slots=16, sess_slots=64,
            nat_mappings=2, nat_backends=4,
            ml_stage="enforce", ml_hidden=4))
        uplink = dp.add_uplink()
        dp.builder.add_route("0.0.0.0/0", uplink, Disposition.REMOTE)
        dp.swap()
        path = tmp_path / "model.json"
        src = MlModelSource(dp, str(path))

        served = 0           # the generation that must be serving
        changed_polls = 0    # polls that saw a changed file
        import time as _t

        def write_and_poll(content_fn, version=None):
            nonlocal changed_polls
            content_fn()
            # mtime granularity: ensure the poll sees the change
            import os as _os

            _os.utime(path, (_t.time(), _t.time() + changed_polls + 1))
            changed_polls += 1
            return src.poll()

        # round 0: good v1 publishes
        assert write_and_poll(
            lambda: save_model(gen_model(1), str(path))) is True
        served = 1

        def assert_serving(version):
            assert int(dp.tables.glb_ml_version) == version
            # the weight plane belongs to the SAME artifact (never a
            # half-applied swap)
            assert int(np.asarray(dp.tables.glb_ml_w1)[12, 0]) == version
            # and the verdicts are that model's: proto 17 scores
            # 17*version, flagged iff > 10
            pv = make_packet_vector([dict(
                src="198.18.0.1", dst="203.0.113.5", proto=17,
                sport=53, dport=9000, rx_if=uplink)], n=8)
            res = dp.process(pv)
            want = 1 if 17 * version > 10 else 0
            assert int(res.stats.ml_flagged) == want

        assert_serving(1)

        # rounds 1..N: seeded mix of injected faults and corrupt files
        refusals = 0
        for r in range(4):
            mode = int(rng.integers(0, 2))
            if mode == 0:
                plan = faults.install(faults.FaultPlan(seed=SEED + r))
                plan.inject("ml.load", times=1, exc=OSError)
                ok = write_and_poll(
                    lambda: save_model(gen_model(9), str(path)))
                assert plan.fired("ml.load") == 1
                faults.uninstall()
            else:
                ok = write_and_poll(
                    lambda: path.write_text('{"format": "garbage'))
            assert ok is False
            refusals += 1
            assert src.degraded
            assert_serving(served)  # previous generation still serving

        # heal: good v2 publishes and degraded clears
        assert write_and_poll(
            lambda: save_model(gen_model(2), str(path))) is True
        served = 2
        assert not src.degraded
        assert_serving(2)

        # ledger conservation: every changed-file poll is accounted
        st = src.stats_snapshot()
        assert st["outcomes"]["loaded"] == 2
        assert sum(st["outcomes"].values()) == changed_polls == \
            refusals + 2


# --------------------------------------------------------------------
# schedule 6: latency-governor faults (ISSUE 13)
# --------------------------------------------------------------------


def _governed_pump(rings, dp, **kw):
    from vpp_tpu.io.governor import LatencyGovernor, PriorityFilter

    gov = LatencyGovernor(kw.pop("slo_us", 300), tick_s=0.005,
                          brownout_ticks=2, recover_ticks=3)
    pump = DataplanePump(dp, rings, mode="persistent", governor=gov,
                         priority=PriorityFilter(ports=(9999,)), **kw)
    return pump, gov


def _push_mixed(rings, rx_if, n_bulk, tag0):
    """n_bulk 4-pkt bulk frames + one 1-pkt priority frame (dport
    9999); returns offered packets."""
    codec = PacketCodec()
    scratch = np.zeros((VEC, rings.rx.snap), np.uint8)
    pkts = 0
    for k in range(n_bulk):
        frames = [make_frame(CLIENT_IP, SERVER_IP, proto=17,
                             sport=tag0 + k, dport=2000 + k * 4 + j)
                  for j in range(4)]
        cols, n = codec.parse(frames, rx_if, scratch)
        assert rings.rx.push(cols, n, payload=scratch)
        pkts += n
    frames = [make_frame(CLIENT_IP, SERVER_IP, proto=17,
                         sport=tag0 + 999, dport=9999)]
    cols, n = codec.parse(frames, rx_if, scratch)
    assert rings.rx.push(cols, n, payload=scratch)
    return pkts + n


def _governed_accounted(pump):
    s = pump.stats
    return (s["pkts"] + s["drops_error"] + s["drops_shutdown"]
            + s["drops_tx_stall"] + s["drops_rx_full"]
            + s["drops_overload"])


class TestGovernorChaos:
    def test_governor_crash_mid_burst_freezes_shape_conserves(self):
        """The ``governor.tick`` seam: the control loop crashing
        mid-burst must WEDGE the governor at the last-known window
        shape — one-way, degraded{component=governor} — while the
        pump keeps forwarding with EXACT packet conservation:
        delivered + drops_overload + drops_tx_stall + drops_shutdown
        (+ error/rx_full) == offered."""
        dp, a, b = _forwarding_dp()
        rings = IORingPair(n_slots=64)
        plan = faults.install(faults.FaultPlan(seed=SEED + 7))
        # a few healthy ticks, then the control loop dies forever
        plan.inject("governor.tick", after=3, times=-1)
        pump, gov = _governed_pump(rings, dp)
        pump.start()
        try:
            offered = 0
            k = 0
            deadline = time.monotonic() + 120.0
            while not gov.snapshot()["wedged"]:
                assert time.monotonic() < deadline, \
                    "governor never wedged"
                offered += _push_mixed(rings, a, 3, 40000 + 16 * k)
                k += 1
                # drain so the 64-slot tx ring never stalls the run
                while rings.tx.peek() is not None:
                    rings.tx.release()
                time.sleep(0.03)
            shape = (gov.snapshot()["fill"], gov.snapshot()["inflight"])
            ticks_at_wedge = gov.snapshot()["ticks"]
            # the wedged governor must freeze: keep offering traffic,
            # the pump stays alive at the frozen shape
            offered += _push_mixed(rings, a, 6, 48000)
            deadline = time.monotonic() + 180.0
            while _governed_accounted(pump) < offered \
                    and time.monotonic() < deadline:
                while rings.tx.peek() is not None:
                    rings.tx.release()
                time.sleep(0.02)
            while rings.tx.peek() is not None:
                rings.tx.release()
            assert pump.stop(join_timeout=60.0)
            s = pump.stats
            assert _governed_accounted(pump) == offered, dict(s)
            assert s["pkts"] > 0  # post-wedge delivery happened
            snap = gov.snapshot()
            assert snap["wedged"]
            assert (snap["fill"], snap["inflight"]) == shape
            assert snap["ticks"] == ticks_at_wedge  # frozen, no drift
            assert plan.fired("governor.tick") >= 3
            # degraded component flips (and ONLY for the governor)
            from vpp_tpu.stats.collector import StatsCollector

            coll = StatsCollector(dp)
            coll.set_pump(pump)
            coll.publish()
            text = "\n".join(
                line for _p, fam in coll.registry.families()
                for line in fam.render())
            assert 'vpp_tpu_degraded{component="governor"} 1' in text
            assert 'vpp_tpu_degraded{component="ring"} 0' in text
        finally:
            pump.stop(join_timeout=30.0)
            rings.close()

    def test_priority_starvation_fault_conserves(self):
        """The ``pump.priority_starve`` seam: flagged frames demoted
        to bulk lose their lane (no express routing, sheddable like
        bulk) but NEVER their conservation — every offered packet is
        delivered or attributed after the schedule."""
        dp, a, b = _forwarding_dp()
        rings = IORingPair(n_slots=64)
        plan = faults.install(faults.FaultPlan(seed=SEED + 8))
        plan.inject("pump.priority_starve", times=-1)
        pump, gov = _governed_pump(rings, dp)
        pump.start()
        try:
            offered = 0
            for k in range(8):
                offered += _push_mixed(rings, a, 3, 52000 + 16 * k)
                time.sleep(0.05)
            deadline = time.monotonic() + 180.0
            while _governed_accounted(pump) < offered \
                    and time.monotonic() < deadline:
                while rings.tx.peek() is not None:
                    rings.tx.release()
                time.sleep(0.02)
            while rings.tx.peek() is not None:
                rings.tx.release()
            assert pump.stop(join_timeout=60.0)
            s = pump.stats
            assert _governed_accounted(pump) == offered, dict(s)
            # the starve seam really demoted the lane: no frame was
            # routed express, and the demotions were counted
            assert plan.fired("pump.priority_starve") >= 8
            assert s["priority_frames"] == 0
            assert s["priority_starved"] >= 8
            assert not gov.snapshot()["wedged"]  # only the lane faulted
        finally:
            pump.stop(join_timeout=30.0)
            rings.close()


class _ShedGovStub:
    """Deterministic governor stand-in for the weighted-shed schedule:
    refuses the first ``refusals`` bulk admissions (each refusal sheds
    exactly one group from the scheduler's hog), then admits
    everything. Implements only the surface the dispatch loop touches
    (tick_due/limits/admit); no control thread, no timing."""

    def __init__(self, refusals):
        self.refusals = refusals
        self.fill = 8

    def bind(self, slots, inflight, queue_cap=None):
        pass

    def tick_due(self):
        return False

    def limits(self):
        return (8, 4, self.refusals > 0)

    def admit(self, is_priority, backlog):
        if is_priority:
            return True
        if self.refusals > 0:
            self.refusals -= 1
            return False
        return True


def _tenant_cls():
    from vpp_tpu.tenancy.sched import (
        TenantClassifier,
        tenant_entries_from_config,
    )

    return TenantClassifier(tenant_entries_from_config([
        {"id": 1, "prefixes": ["10.50.0.0/16"], "weight": 1},
        {"id": 2, "prefixes": ["10.60.0.0/16"], "weight": 8},
    ]))


def _push_tenant(rings, rx_if, src, n_frames, per, tag0):
    codec = PacketCodec()
    scratch = np.zeros((VEC, rings.rx.snap), np.uint8)
    pkts = 0
    for k in range(n_frames):
        frames = [make_frame(src, SERVER_IP, proto=17,
                             sport=tag0 + k, dport=1000 + k * per + j)
                  for j in range(per)]
        cols, n = codec.parse(frames, rx_if, scratch)
        assert rings.rx.push(cols, n, payload=scratch)
        pkts += n
    return pkts


class TestTenantChaos:
    def test_tenant_starve_fault_conserves(self):
        """The ``pump.tenant_starve`` seam (ISSUE 14): tenant
        classification demoted to the default tenant loses the
        weighted lane but NEVER conservation — every offered packet
        is delivered or attributed, the demotions are counted, and
        all lane accounting lands under tenant 0."""
        dp, a, b = _forwarding_dp()
        rings = IORingPair(n_slots=64)
        plan = faults.install(faults.FaultPlan(seed=SEED + 9))
        plan.inject("pump.tenant_starve", times=-1)
        pump = DataplanePump(dp, rings, mode="dispatch",
                             max_batch=VEC, tenants=_tenant_cls())
        pump.start()
        try:
            offered = 0
            for k in range(6):
                offered += _push_tenant(rings, a, "10.50.1.1", 1, 4,
                                        30000 + k)
                offered += _push_tenant(rings, a, "10.60.1.1", 1, 4,
                                        31000 + k)
                time.sleep(0.02)
            deadline = time.monotonic() + 120.0
            while pump.stats["pkts"] < offered \
                    and time.monotonic() < deadline:
                while rings.tx.peek() is not None:
                    rings.tx.release()
                time.sleep(0.01)
            while rings.tx.peek() is not None:
                rings.tx.release()
            assert pump.stop(join_timeout=60.0)
            s = pump.stats
            assert s["pkts"] == offered  # EXACT conservation
            assert plan.fired("pump.tenant_starve") == 12
            assert s["tenant_starved"] == 12
            tio = pump.tenant_io_snapshot()
            # every frame was demoted: only the default lane exists
            assert set(tio["io"]) == {0}
            assert tio["io"][0]["pkts"] == offered
        finally:
            pump.stop(join_timeout=30.0)
            rings.close()

    def test_brownout_sheds_per_tenant_weighted_not_fifo(self):
        """The ISSUE 14 fairness-under-overload contract: with tenant
        lanes, brownout shedding picks the tenant with the most
        backlog PER UNIT WEIGHT — not arrival order. Tenant 2 (weight
        8, small backlog) pushes FIRST, so FIFO shedding would eat its
        frames; the hog (tenant 1: weight 1, deep backlog) arrives
        after and must absorb EVERY shed, attributed drops_overload
        with exact conservation."""
        dp, a, b = _forwarding_dp()
        rings = IORingPair(n_slots=64)
        gov = _ShedGovStub(refusals=1)
        pump = DataplanePump(dp, rings, mode="dispatch",
                             max_batch=VEC, governor=gov,
                             tenants=_tenant_cls())
        # the whole backlog is queued BEFORE the pump starts, oldest
        # frames belonging to the light tenant; the hog's 192-pkt
        # backlog fits one shed group (< max_batch=VEC), so ONE
        # refusal sheds exactly the hog's queue and nothing else
        offered = _push_tenant(rings, a, "10.60.1.1", 6, 4, 41000)
        offered += _push_tenant(rings, a, "10.50.1.1", 12, 16, 40000)
        pump.start()
        try:
            deadline = time.monotonic() + 120.0
            while pump.stats["pkts"] + pump.stats["drops_overload"] \
                    < offered and time.monotonic() < deadline:
                while rings.tx.peek() is not None:
                    rings.tx.release()
                time.sleep(0.01)
            while rings.tx.peek() is not None:
                rings.tx.release()
            assert pump.stop(join_timeout=60.0)
            s = pump.stats
            assert s["pkts"] + s["drops_overload"] == offered
            assert s["drops_overload"] == 192
            tio = pump.tenant_io_snapshot()
            # weighted, not FIFO: the oldest frames (tenant 2) were
            # never shed; the hog absorbed every drop — and the light
            # tenant's packets were all DELIVERED
            assert tio["io"][1]["shed_pkts"] == 192
            assert tio["io"][2]["shed_pkts"] == 0
            assert tio["io"][2]["pkts"] == 24
            assert s["pkts"] == 24
            assert gov.refusals == 0
        finally:
            pump.stop(join_timeout=30.0)
            rings.close()
