"""VPPTCP renderer + session-rule engine tests.

Reference model: renderer/vpptcp/vpptcp_renderer_test.go — render pod
policies, then assert *connection* allow/deny semantics against the
installed session-rule tables. LOCAL scope filters a namespace's
outbound connects (ingress orientation: rules sit where traffic enters
the vswitch from the app); GLOBAL scope filters inbound accepts from
outside the node. Batched minimal deltas + resync reconciliation.
"""

import ipaddress


from vpp_tpu.hoststack import (
    RuleAction,
    RuleScope,
    SessionRule,
    SessionRuleEngine,
)
from vpp_tpu.hoststack.session_rules import GLOBAL_NS
from vpp_tpu.ir import Action, ContivRule, Protocol
from vpp_tpu.renderer.vpptcp import VpptcpRenderer
from vpp_tpu.pipeline.vector import ip4

NS = {("default", "client"): 1, ("default", "server"): 2, ("prod", "web"): 3}

POD_CLIENT = ("default", "client")
POD_SERVER = ("default", "server")
CLIENT_IP = ipaddress.ip_network("10.1.1.2/32")
SERVER_IP = ipaddress.ip_network("10.1.1.3/32")


def make_renderer():
    engine = SessionRuleEngine(capacity=512)
    return VpptcpRenderer(engine, lambda pod: NS.get(pod, -1)), engine


def out_conn(ns, proto, lcl_ip, lcl_port, rmt_ip, rmt_port):
    return (ns, proto, ip4(lcl_ip), lcl_port, ip4(rmt_ip), rmt_port)


def in_conn(proto, lcl_ip, lcl_port, rmt_ip, rmt_port):
    return (proto, ip4(lcl_ip), lcl_port, ip4(rmt_ip), rmt_port)


def test_engine_specificity_first_match():
    eng = SessionRuleEngine(capacity=64)
    eng.apply(add=[
        SessionRule(scope=int(RuleScope.LOCAL), appns_index=1,
                    transport_proto=6, lcl_net=0, lcl_plen=0,
                    rmt_net=ip4("10.0.0.0"), rmt_plen=8,
                    lcl_port=0, rmt_port=80,
                    action=int(RuleAction.ALLOW)),
        SessionRule(scope=int(RuleScope.LOCAL), appns_index=1,
                    transport_proto=6, lcl_net=0, lcl_plen=0,
                    rmt_net=0, rmt_plen=0, lcl_port=0, rmt_port=0,
                    action=int(RuleAction.DENY)),
    ])
    got = eng.check_connect([
        out_conn(1, 6, "10.1.1.2", 9999, "10.2.3.4", 80),   # specific allow
        out_conn(1, 6, "10.1.1.2", 9999, "10.2.3.4", 22),   # deny-all
        out_conn(2, 6, "10.1.1.2", 9999, "10.2.3.4", 22),   # other ns: allow
        out_conn(1, 17, "10.1.1.2", 9999, "10.2.3.4", 22),  # UDP: no rule
    ])
    assert got.tolist() == [True, False, True, True]


def test_engine_direction_scoping():
    """LOCAL rules never see accepts; GLOBAL rules never see connects."""
    eng = SessionRuleEngine(capacity=64)
    eng.apply(add=[
        # ns 7 may not connect anywhere
        SessionRule(scope=int(RuleScope.LOCAL), appns_index=7,
                    transport_proto=6, lcl_net=0, lcl_plen=0,
                    rmt_net=0, rmt_plen=0, lcl_port=0, rmt_port=0,
                    action=int(RuleAction.DENY)),
        # node accepts only to 10.1.1.3:80
        SessionRule(scope=int(RuleScope.GLOBAL), appns_index=GLOBAL_NS,
                    transport_proto=6, lcl_net=ip4("10.1.1.3"), lcl_plen=32,
                    rmt_net=0, rmt_plen=0, lcl_port=80, rmt_port=0,
                    action=int(RuleAction.ALLOW)),
        SessionRule(scope=int(RuleScope.GLOBAL), appns_index=GLOBAL_NS,
                    transport_proto=6, lcl_net=0, lcl_plen=0,
                    rmt_net=0, rmt_plen=0, lcl_port=0, rmt_port=0,
                    action=int(RuleAction.DENY)),
    ])
    got_out = eng.check_connect([
        out_conn(7, 6, "1.1.1.1", 9, "2.2.2.2", 80),  # ns7 denied
        out_conn(8, 6, "1.1.1.1", 9, "2.2.2.2", 80),  # ns8: global doesn't apply
    ])
    assert got_out.tolist() == [False, True]
    got_in = eng.check_accept([
        in_conn(6, "10.1.1.3", 80, "9.9.9.9", 555),   # allowed accept
        in_conn(6, "10.1.1.3", 22, "9.9.9.9", 555),   # denied port
        in_conn(6, "10.1.1.4", 80, "9.9.9.9", 555),   # denied target
    ])
    assert got_in.tolist() == [True, False, False]


def test_renderer_policy_to_session_rules():
    r, eng = make_renderer()
    # server accepts TCP/80 only from client (ingress orientation: the
    # pod's egress list describes traffic it RECEIVES)
    txn = r.new_txn()
    txn.render(POD_SERVER, SERVER_IP, ingress=[], egress=[
        ContivRule(action=Action.PERMIT, src_network=CLIENT_IP,
                   protocol=Protocol.TCP, dest_port=80),
        ContivRule(action=Action.DENY),
    ])
    txn.render(POD_CLIENT, CLIENT_IP, ingress=[], egress=[])
    txn.commit()
    assert eng.num_rules > 0

    # client's outbound connects (LOCAL scope, client's namespace)
    client_ns = NS[POD_CLIENT]
    got = eng.check_connect([
        out_conn(client_ns, 6, "10.1.1.2", 9999, "10.1.1.3", 80),  # → server:80 ok
        out_conn(client_ns, 6, "10.1.1.2", 9999, "10.1.1.3", 22),  # → server:22 denied
        out_conn(client_ns, 6, "10.1.1.2", 9999, "8.8.8.8", 443),  # elsewhere ok
    ])
    assert got.tolist() == [True, False, True]

    # inbound accepts from outside the node (GLOBAL scope)
    got_in = eng.check_accept([
        in_conn(6, "10.1.1.3", 80, "10.1.1.2", 5555),  # client → server:80 ok
        in_conn(6, "10.1.1.3", 80, "10.9.9.9", 5555),  # stranger denied
        in_conn(6, "10.1.1.3", 22, "10.1.1.2", 5555),  # wrong port denied
    ])
    assert got_in.tolist() == [True, False, False]


def test_renderer_batched_delta_updates():
    r, eng = make_renderer()
    txn = r.new_txn()
    txn.render(POD_SERVER, SERVER_IP, ingress=[], egress=[
        ContivRule(action=Action.PERMIT, src_network=CLIENT_IP,
                   protocol=Protocol.TCP, dest_port=80),
        ContivRule(action=Action.DENY),
    ])
    txn.commit()
    before = set(eng.dump())

    # a policy on another pod adds new rules (the ingress fold pins the
    # new pod's restrictions into every sender's table) but must only
    # ADD at the wire level — existing rules stay installed untouched
    applied = []
    orig_apply = eng.apply
    eng.apply = lambda add=(), delete=(): (
        applied.append((set(add), set(delete))), orig_apply(add, delete)
    )[1]
    txn2 = r.new_txn()
    txn2.render(("prod", "web"), ipaddress.ip_network("10.1.1.9/32"),
                ingress=[], egress=[
        ContivRule(action=Action.PERMIT, protocol=Protocol.TCP, dest_port=443),
        ContivRule(action=Action.DENY),
    ])
    txn2.commit()
    eng.apply = orig_apply
    after = set(eng.dump())
    assert before <= after, "existing rules must survive an unrelated update"
    assert len(applied) == 1, "one batched apply per commit"
    add, delete = applied[0]
    assert not delete, "unrelated update must not delete installed rules"
    assert add == after - before, "wire delta is exactly the new rules"
    assert any(x.appns_index == NS[("prod", "web")] for x in after)

    # removing the server pod deletes exactly its namespace's rules
    txn3 = r.new_txn()
    txn3.render(POD_SERVER, SERVER_IP, ingress=[], egress=[], removed=True)
    txn3.commit()
    final = set(eng.dump())
    assert not any(x.appns_index == NS[POD_SERVER] for x in final)
    assert any(x.appns_index == NS[("prod", "web")] for x in final)


def test_renderer_resync_reconciles_stale_rules():
    r, eng = make_renderer()
    # stale rule left over from "before restart"
    stale = SessionRule(scope=int(RuleScope.LOCAL), appns_index=42,
                        transport_proto=6, lcl_net=0, lcl_plen=0,
                        rmt_net=0, rmt_plen=0, lcl_port=0, rmt_port=0,
                        action=int(RuleAction.DENY), tag="stale")
    eng.apply(add=[stale])

    txn = r.new_txn(resync=True)
    txn.render(POD_SERVER, SERVER_IP, ingress=[], egress=[
        ContivRule(action=Action.PERMIT, src_network=CLIENT_IP,
                   protocol=Protocol.TCP, dest_port=80),
        ContivRule(action=Action.DENY),
    ])
    txn.commit()
    dump = eng.dump()
    assert stale not in dump
    assert any(x.appns_index == NS[POD_SERVER] for x in dump)


def test_icmp_rules_skipped_any_proto_expands():
    r, eng = make_renderer()
    txn = r.new_txn()
    txn.render(POD_SERVER, SERVER_IP, ingress=[], egress=[
        ContivRule(action=Action.PERMIT, src_network=CLIENT_IP,
                   protocol=Protocol.ANY),
        ContivRule(action=Action.PERMIT, protocol=Protocol.ICMP),
        ContivRule(action=Action.DENY),
    ])
    txn.commit()
    protos = {x.transport_proto for x in eng.dump()
              if x.appns_index == NS[POD_SERVER]}
    assert protos == {6, 17}  # ANY → TCP+UDP; ICMP skipped at session layer
