"""HA kvstore fencing: quorum witness, fencing epochs, partition safety.

VERDICT r4 weak #5 / Next #4: the unfenced warm standby self-promoted on
unreachability, so a both-alive partition yielded TWO writable stores —
a correctness hazard for the store that coordinates LockstepDriver
collective epochs. The reference never faces this because etcd's raft
quorum refuses writes on the minority side
(/root/reference/k8s/contiv-vpp.yaml:72-114). These tests prove the
2-replicas + arbiter construction (kvstore/witness.py) restores that
guarantee:

  * standby-side partition (primary healthy): claim denied, standby
    stays read-only, resumes following on heal — ONE writable store;
  * primary isolated: it self-demotes BEFORE the standby's claim can be
    granted — never two writable stores, sampled continuously;
  * a client CAS sequence (the LockstepDriver epoch pattern) survives
    the failover with no lost or duplicated update;
  * stale/newer fencing epochs on the wire: stale writes rejected, a
    newer-epoch write demotes a superseded ex-primary on the spot.

Partitions are injected with a real TCP relay (cut = reset both sides,
refuse new streams) so every process keeps RUNNING — the exact
both-alive scenario the round-4 design forked on.
"""

from __future__ import annotations

import os
import socket
import threading
import time

import pytest

from vpp_tpu.kvstore.client import RemoteKVStore
from vpp_tpu.kvstore.replica import HaCoordinator
from vpp_tpu.kvstore.server import KVServer
from vpp_tpu.kvstore.store import KVStore
from vpp_tpu.kvstore.witness import (
    PrimaryGuard, QuorumWitness, WitnessClient, WitnessUnreachable,
)

# generous on the one-core CI host; partition mechanics are
# event-driven so success is fast, only failures wait this long
WAIT = 30.0


def wait_for(pred, timeout=WAIT, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.01)
    raise AssertionError(f"timed out waiting for {msg}")


class Relay:
    """TCP forwarder standing in for one network link. cut() resets
    every live stream and refuses new ones (peers stay alive — this is
    a partition, not a crash); heal() restores forwarding."""

    def __init__(self, target_port: int):
        self.target_port = target_port
        self.blocked = False
        self._socks: set = set()
        self._lock = threading.Lock()
        self._ls = socket.socket()
        self._ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._ls.bind(("127.0.0.1", 0))
        self._ls.listen(16)
        self.port = self._ls.getsockname()[1]
        threading.Thread(target=self._accept_loop, daemon=True).start()

    def _accept_loop(self):
        while True:
            try:
                a, _ = self._ls.accept()
            except OSError:
                return
            if self.blocked:
                a.close()
                continue
            try:
                b = socket.create_connection(
                    ("127.0.0.1", self.target_port), timeout=5)
            except OSError:
                a.close()
                continue
            with self._lock:
                self._socks.update((a, b))
            threading.Thread(target=self._pump, args=(a, b),
                             daemon=True).start()
            threading.Thread(target=self._pump, args=(b, a),
                             daemon=True).start()

    def _pump(self, src, dst):
        try:
            while True:
                data = src.recv(65536)
                if not data:
                    break
                dst.sendall(data)
        except OSError:
            pass
        finally:
            for s in (src, dst):
                try:
                    s.close()
                except OSError:
                    pass

    def cut(self):
        self.blocked = True
        with self._lock:
            socks, self._socks = self._socks, set()
        for s in socks:
            try:
                s.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                             b"\x01\x00\x00\x00\x00\x00\x00\x00")
                s.close()  # RST: peers learn immediately, nothing hangs
            except OSError:
                pass

    def heal(self):
        self.blocked = False

    def close(self):
        self.cut()
        self._ls.close()


# --- witness unit semantics ---
class TestWitness:
    def test_adopt_renew_claim(self, tmp_path):
        w = QuorumWitness(persist_path=str(tmp_path / "w.json")).start()
        try:
            c = WitnessClient(w.address)
            # first renew adopts
            assert c.renew("p:1", 0, ttl=2.0)["ok"] is True
            # someone else at the same epoch: rejected while lease fresh
            assert c.renew("q:1", 0, ttl=2.0)["ok"] is False
            assert c.claim("q:1", ttl=2.0)["granted"] is False
            # current primary re-claiming never bumps the epoch
            r = c.claim("p:1", ttl=0.3)
            assert r == {"granted": True, "epoch": 0}
            # lease lapse -> claim granted with a BUMPED epoch
            time.sleep(0.4)
            r = c.claim("q:1", ttl=2.0)
            assert r["granted"] is True and r["epoch"] == 1
            # the superseded primary's renew is now rejected
            assert c.renew("p:1", 0, ttl=2.0)["ok"] is False
        finally:
            w.close()

    def test_restart_grace_and_persistence(self, tmp_path):
        path = str(tmp_path / "w.json")
        w = QuorumWitness(persist_path=path).start()
        c = WitnessClient(w.address)
        assert c.claim("p:1", ttl=0.8)["granted"] is True  # epoch 1
        w.close()
        # restarted witness: epoch survives, and the lease gets a full
        # fresh ttl — an instant claim by the standby must NOT win just
        # because the witness rebooted
        w2 = QuorumWitness(persist_path=path).start()
        try:
            c2 = WitnessClient(w2.address)
            assert c2.status()["epoch"] == 1
            assert c2.claim("q:1", ttl=0.8)["granted"] is False
            time.sleep(1.0)  # grace = persisted ttl
            r = c2.claim("q:1", ttl=0.8)
            assert r["granted"] is True and r["epoch"] == 2
        finally:
            w2.close()

    def test_unreachable_raises(self):
        c = WitnessClient("127.0.0.1:1", timeout=0.5)
        with pytest.raises(WitnessUnreachable):
            c.status()

    def test_fresh_witness_adopts_surviving_primary_epoch(self, tmp_path):
        """Witness restart WITHOUT its persist file (node reschedule
        on a hostPath): its epoch resets to 0. The surviving primary's
        renew at its higher store epoch must be ADOPTED — refusing it
        demotes the only primary as 'superseded' with rsp.primary=None
        (nobody to re-follow) while the standby's heartbeats keep
        succeeding against the read-only primary, wedging the pair
        read-only forever (ADVICE r5 medium)."""
        w = QuorumWitness(persist_path=str(tmp_path / "w1.json")).start()
        c = WitnessClient(w.address)
        # two failovers: the fleet's fencing epoch is now 2
        assert c.claim("p:1", ttl=2.0)["granted"] is True
        assert c.renew("p:1", 1, ttl=0.2)["ok"] is True
        time.sleep(0.3)
        assert c.claim("q:1", ttl=2.0)["epoch"] == 2
        w.close()
        # fresh state: no persist file carried over
        w2 = QuorumWitness(persist_path=str(tmp_path / "w2.json")).start()
        try:
            c2 = WitnessClient(w2.address)
            r = c2.renew("q:1", 2, ttl=2.0)
            assert r["ok"] is True, \
                "lone renewer at a higher epoch is the surviving " \
                "authority, not an impostor"
            assert r["epoch"] == 2  # adopted, not reset
            # and the adopted epoch fences exactly like a persisted
            # one: a stale pre-failover primary stays rejected
            assert c2.renew("p:1", 1, ttl=2.0)["ok"] is False
        finally:
            w2.close()
        # ordering hazard: the STALE ex-primary renews FIRST against
        # yet another fresh witness. It wins transiently (the witness
        # can't know better), but adoption is highest-epoch-wins, so
        # the true primary's next renew supersedes it — p must not be
        # able to permanently fence q out just by racing the restart.
        w3 = QuorumWitness(persist_path=str(tmp_path / "w3.json")).start()
        try:
            c3 = WitnessClient(w3.address)
            assert c3.renew("p:1", 1, ttl=2.0)["ok"] is True  # stale won
            r = c3.renew("q:1", 2, ttl=2.0)
            assert r["ok"] is True and r["epoch"] == 2  # superseded
            assert c3.renew("p:1", 1, ttl=2.0)["ok"] is False
        finally:
            w3.close()


# --- fencing epochs on the data path ---
class TestFenceWire:
    def test_stale_fence_rejected_then_refreshed(self):
        srv = KVServer(host="127.0.0.1", port=0).start()
        try:
            c = RemoteKVStore("127.0.0.1", srv.port, request_timeout=5.0)
            assert c._epoch == 0
            c.put("k", 1)
            # epoch moves server-side (a promotion elsewhere); the
            # client's next write is stale -> transparent refresh+retry
            srv.store.fencing_epoch = 3
            assert c.put("k", 2) >= 1
            assert c._epoch == 3
            c.close()
        finally:
            srv.close()

    def test_newer_fence_demotes_superseded_primary(self):
        """The in-band beacon: a client that has seen epoch E+1 writes
        to a still-writable ex-primary at epoch E -> the server demotes
        itself on the spot instead of accepting cross-history state."""
        srv = KVServer(host="127.0.0.1", port=0).start()
        try:
            c = RemoteKVStore("127.0.0.1", srv.port, request_timeout=2.0)
            c._epoch = 7  # learned from the new primary
            with pytest.raises((RuntimeError, TimeoutError)):
                c.put("k", 1)  # single endpoint: no rotation possible
            assert srv.read_only is True
            assert srv.store.get("k") is None
            c.close()
        finally:
            srv.close()

    def test_spurious_inband_demotion_heals_on_next_renewal(self):
        """ADVICE r5: a write carrying fence > epoch demotes the primary
        in-band (server.py) even when the witness never granted a claim
        — e.g. a buggy or malicious client minting a future epoch. The
        guard must re-assert writability on its NEXT successful renewal
        at its own epoch (a successful renew proves the lease is still
        ours, so no second history exists); without that, the spurious
        demotion would be a permanent read-only outage."""
        w = QuorumWitness(host="127.0.0.1").start()
        srv = KVServer(host="127.0.0.1", port=0).start()
        guard = None
        try:
            guard = PrimaryGuard(srv, w.address,
                                 f"127.0.0.1:{srv.port}", ttl=0.6).start()
            assert srv.read_only is False  # first renewal succeeded
            # in-band demotion: a client that claims to have seen a
            # NEWER primary, while the witness lease is in fact ours
            c = RemoteKVStore("127.0.0.1", srv.port, request_timeout=2.0)
            c._epoch = 99
            with pytest.raises((RuntimeError, TimeoutError)):
                c.put("k", 1)
            # demoted on the spot, and the write never landed
            assert srv.store.get("k") is None
            # the guard's next renewal at our (real) epoch heals it
            wait_for(lambda: not srv.read_only,
                     msg="writable again after a proven renewal")
            assert guard.superseded.is_set() is False
            c.close()
        finally:
            if guard:
                guard.stop()
            srv.close()
            w.close()

    def test_guard_start_fails_closed(self):
        """A server that has never held the witness lease must not take
        a single write: a restarted ex-primary partitioned from the
        witness would otherwise serve its stale epoch writable while
        the promoted standby owns the real history (fork)."""
        srv = KVServer(host="127.0.0.1", port=0).start()
        w = QuorumWitness(host="127.0.0.1").start()
        waddr = w.address
        w.close()  # witness down before the guard's first renewal
        guard = PrimaryGuard(srv, waddr, f"127.0.0.1:{srv.port}",
                             ttl=1.5).start()
        w2 = None
        try:
            assert srv.read_only is True
            # witness returns, lease free at our epoch: authority
            # proven -> writable (a blip, not a fork)
            host, port = waddr.rsplit(":", 1)
            w2 = QuorumWitness(host=host, port=int(port)).start()
            wait_for(lambda: not srv.read_only,
                     msg="writable once authority is proven")
        finally:
            guard.stop()
            if w2:
                w2.close()
            srv.close()

    def test_fence_survives_store_restart(self, tmp_path):
        path = str(tmp_path / "kv.json")
        s = KVStore(persist_path=path)
        s.put("a", 1)
        s.fencing_epoch = 4
        s.save()
        s2 = KVStore(persist_path=path)
        assert s2.fencing_epoch == 4
        with pytest.raises(ValueError):
            s2.fencing_epoch = 3  # may only advance


# --- the partition scenarios ---
# Both roles are assembled through HaCoordinator — the exact wiring
# cmd/kvserver.py main() deploys — so the role swaps under test are the
# deployed ones, not a test-local reimplementation.
def _primary(witness_addr, ttl, promote_after=10.0):
    srv = KVServer(host="127.0.0.1", port=0).start()
    ha = HaCoordinator(srv, witness_addr, f"127.0.0.1:{srv.port}",
                       fence_ttl=ttl, promote_after=promote_after).start()
    return srv, ha


def _standby(primary_port, witness_addr, ttl, promote_after):
    srv = KVServer(host="127.0.0.1", port=0).start()
    ha = HaCoordinator(srv, witness_addr, f"127.0.0.1:{srv.port}",
                       fence_ttl=ttl, promote_after=promote_after,
                       follow=f"127.0.0.1:{primary_port}").start()
    return srv, ha


class TestPartitions:
    # generous on the one-core CI host: the no-promotion assertion only
    # holds while the primary's guard thread actually gets scheduled
    # often enough to renew — a tight ttl turns host load into a
    # legitimate (but unwanted-here) lease expiry. The race-amplified
    # run (VPP_TPU_RACE: microsecond thread preemption) starves
    # threads even harder, so it gets a longer lease.
    TTL = 8.0 if os.environ.get("VPP_TPU_RACE") else 4.0
    PROMOTE_AFTER = 1.5

    def test_standby_side_partition_never_promotes(self, tmp_path):
        """S<->P cut while P<->W stays up: the primary keeps its lease,
        the standby's claim is denied, and the system keeps exactly one
        writable store. On heal the standby RESUMES following."""
        w = QuorumWitness().start()
        psrv, pha = _primary(w.address, self.TTL)
        relay = Relay(psrv.port)
        ssrv = sha = None
        try:
            pc = RemoteKVStore("127.0.0.1", psrv.port, request_timeout=5.0)
            pc.put("before", 1)
            ssrv, sha = _standby(relay.port, w.address, self.TTL,
                                 self.PROMOTE_AFTER)
            wait_for(lambda: ssrv.store.get("before") == 1,
                     msg="initial replication")

            relay.cut()
            # the standby notices within promote_after and tries to
            # claim; the witness must deny. Gate on the denial counter
            # (3 observed claim attempts) instead of a wall-clock
            # sleep sized to worst-case retry pacing — the flake was
            # the sleep electing a loaded host's schedule:
            wait_for(lambda: sha.replicator.claim_denials >= 3,
                     timeout=self.PROMOTE_AFTER + 6 * self.TTL,
                     msg="three denied claims")
            assert not sha.replicator.promoted.is_set(), \
                "standby promoted despite a live primary (FORK)"
            assert ssrv.read_only is True
            assert pha.guard.superseded.is_set() is False
            pc.put("during", 2)  # the one writable store still writes

            relay.heal()
            wait_for(lambda: ssrv.store.get("during") == 2,
                     msg="standby resumed following after heal")
            assert ssrv.read_only is True
            pc.close()
        finally:
            if sha:
                sha.stop()
            if ssrv:
                ssrv.close()
            pha.stop()
            relay.close()
            psrv.close()
            w.close()

    @pytest.mark.slow  # ~35 s and timing-sensitive under load (30 s promote wait); standby-side fencing stays fast below, full matrix in make test-race
    def test_isolated_primary_demotes_before_standby_claims(self):
        """P loses BOTH links (to W and to S) but stays alive: it must
        stop accepting writes strictly before S's claim can be granted.
        A sampler thread asserts 'two writable stores' never happens.
        After the heal, the superseded ex-primary must automatically
        re-follow the winner (HaCoordinator) — the pair self-heals back
        to primary+standby with no operator action."""
        w = QuorumWitness().start()
        wrelay = Relay(w.port)  # P -> W goes through this
        psrv = KVServer(host="127.0.0.1", port=0).start()
        pha = HaCoordinator(psrv, f"127.0.0.1:{wrelay.port}",
                            f"127.0.0.1:{psrv.port}",
                            fence_ttl=self.TTL).start()
        prelay = Relay(psrv.port)  # S -> P goes through this
        ssrv = sha = None
        overlap = []
        stop_sampling = threading.Event()

        def sample():
            while not stop_sampling.is_set():
                if ssrv is not None and \
                        not psrv.read_only and not ssrv.read_only:
                    overlap.append(time.monotonic())
                time.sleep(0.005)

        sampler = threading.Thread(target=sample, daemon=True)
        sampler.start()
        try:
            pc = RemoteKVStore("127.0.0.1", psrv.port, request_timeout=5.0)
            pc.put("seed", 1)
            ssrv, sha = _standby(prelay.port, w.address, self.TTL,
                                 self.PROMOTE_AFTER)
            wait_for(lambda: ssrv.store.get("seed") == 1,
                     msg="initial replication")

            # isolate P completely (both links), everything keeps running
            wrelay.cut()
            prelay.cut()
            wait_for(lambda: psrv.read_only,
                     msg="isolated primary self-demoted")
            wait_for(lambda: sha.replicator.promoted.is_set(),
                     msg="standby promoted via granted claim")
            assert ssrv.read_only is False
            assert sha.replicator.epoch == 1
            assert ssrv.store.fencing_epoch == 1
            assert not overlap, \
                f"two writable stores observed at {overlap}"

            # heal P's witness link: its next renewal is rejected
            # (epoch moved) -> permanently superseded, still read-only,
            # and HaCoordinator re-follows the new primary: a write on
            # S must now replicate INTO the ex-primary's store
            wrelay.heal()
            wait_for(lambda: pha.guard.superseded.is_set(),
                     msg="ex-primary learned it was superseded")
            assert psrv.read_only is True
            sc = RemoteKVStore("127.0.0.1", ssrv.port, request_timeout=5.0)
            sc.put("after-failover", 42)
            wait_for(lambda: psrv.store.get("after-failover") == 42,
                     msg="ex-primary auto-refollowed the winner")
            assert psrv.read_only is True
            sc.close()
            pc.close()
        finally:
            stop_sampling.set()
            sampler.join(timeout=5)
            if sha:
                sha.stop()
            if ssrv:
                ssrv.close()
            pha.stop()
            prelay.close()
            wrelay.close()
            psrv.close()
            w.close()

    def test_cas_sequence_survives_failover(self):
        """The LockstepDriver pattern: a client advancing an epoch key
        by CAS through the HA pair. Across a primary death + fenced
        promotion, every CAS must apply exactly once — the final value
        equals the number of successful CAS calls (no lost update, no
        fork)."""
        w = QuorumWitness().start()
        psrv, pha = _primary(w.address, self.TTL)
        ssrv, sha = _standby(psrv.port, w.address, self.TTL,
                             self.PROMOTE_AFTER)
        client = None
        try:
            client = RemoteKVStore(
                "127.0.0.1", psrv.port, request_timeout=20.0,
                reconnect_timeout=20.0,
                fallbacks=[("127.0.0.1", ssrv.port)])
            client.put("epoch", 0)
            wait_for(lambda: ssrv.store.get("epoch") == 0,
                     msg="replication")
            applied = 0
            for i in range(6):
                if i == 3:
                    # primary dies mid-sequence (a crash, not a
                    # partition: the partition cases are above)
                    pha.stop()
                    psrv.close()
                # CAS with retry across the failover window; a CAS that
                # raises may still have applied server-side (conn died
                # post-commit) — re-read to decide, like any etcd user
                deadline = time.monotonic() + WAIT
                while True:
                    try:
                        if client.compare_and_put("epoch", i, i + 1):
                            applied += 1
                        break
                    except (ConnectionError, TimeoutError, RuntimeError):
                        if client.get("epoch") == i + 1:
                            applied += 1
                            break
                        if time.monotonic() > deadline:
                            raise
                        time.sleep(0.2)
            assert applied == 6
            assert client.get("epoch") == 6
            # the writes after the failover landed on the promoted
            # standby under the bumped fencing epoch
            assert ssrv.read_only is False
            assert ssrv.store.get("epoch") == 6
            assert ssrv.store.fencing_epoch == 1
        finally:
            if client:
                client.close()
            sha.stop()
            ssrv.close()
            w.close()


class TestWitnessRobustness:
    def test_garbage_on_the_wire_never_corrupts_arbitration(self):
        """The witness is the cluster's tie-breaker: random bytes,
        truncated frames, wrong-typed fields and oversized lines must
        neither crash it nor move its (epoch, primary, lease) state."""
        import json
        import random

        w = QuorumWitness().start()
        try:
            c = WitnessClient(w.address)
            assert c.renew("p:1", 0, ttl=30.0)["ok"] is True
            before = c.status()

            rng = random.Random(5)
            payloads = [
                b"", b"\n", b"\x00" * 64, b"not json\n",
                b"{}\n", b'{"op": "claim"}\n',  # missing fields
                b'{"op": "renew", "node": 1, "epoch": "x"}\n',
                b'{"op": 12}\n', b'[1,2,3]\n',
                b'{"op": "unknown-verb"}\n',
                json.dumps({"op": "claim", "node": "evil",
                            "ttl": "NaN"}).encode() + b"\n",
                bytes(rng.randrange(256) for _ in range(4096)) + b"\n",
            ]
            for p in payloads:
                s = socket.create_connection(
                    ("127.0.0.1", w.port), timeout=5)
                try:
                    s.sendall(p)
                    # short: newline-less payloads never get a reply
                    # (the witness is still blocked in readline) and
                    # per-payload 5 s recv timeouts would stall this
                    # unit test ~10 s on the one-core host
                    s.settimeout(0.4)
                    try:
                        s.recv(65536)  # error reply or close — either
                    except OSError:
                        pass
                finally:
                    s.close()
            after = c.status()
            assert after["epoch"] == before["epoch"] == 0
            assert after["primary"] == "p:1"
            # and the real protocol still works
            assert c.renew("p:1", 0, ttl=30.0)["ok"] is True
        finally:
            w.close()

    def test_nan_ttl_rejected_even_when_claim_would_be_granted(self):
        """The deadly variant: on a FREE lease a NaN ttl would win the
        claim and set a deadline no comparison can ever pass — the
        arbiter wedged forever, failover impossible. It must be
        rejected at the protocol boundary, leaving arbitration
        fully functional."""
        import json

        w = QuorumWitness().start()  # no primary: claims are grantable
        try:
            for evil in ("NaN", "Infinity", -1, 0):
                s = socket.create_connection(
                    ("127.0.0.1", w.port), timeout=5)
                s.sendall(json.dumps(
                    {"op": "claim", "node": "evil", "ttl": evil}
                ).encode() + b"\n")
                s.settimeout(5)
                rsp = json.loads(s.recv(65536))
                s.close()
                assert rsp.get("granted") is not True, (evil, rsp)
            c = WitnessClient(w.address)
            assert c.status()["primary"] is None
            # a legitimate claim still wins, and expiry still works
            assert c.claim("good:1", ttl=0.3)["granted"] is True
            time.sleep(0.4)
            assert c.claim("other:1", ttl=5.0)["granted"] is True
        finally:
            w.close()
