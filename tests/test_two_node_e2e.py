"""Two-node e2e with real processes (VERDICT r2 Next #4).

The full control+data story of the reference's two_node_two_pods robot
suite (tests/robot/suites/two_node_two_pods.robot), with real process
boundaries everywhere the deployment has them:

  * the FENCED store trio as three subprocesses — quorum witness,
    primary kvserver, warm-standby kvserver (the chart's three
    Deployments; the etcd analog with its raft-quorum guarantee
    rebuilt as 2 replicas + arbiter, kvstore/witness.py) — agents'
    store_url lists both endpoints,
  * per node: a vpp-tpu-agent subprocess and a vpp-tpu-io subprocess
    (launched from the agent's published IO plan, exactly as
    vpp-tpu-init does),
  * a veth pair as the inter-node fabric: each node's IO daemon binds
    one leg as its uplink; node-to-node pod traffic rides VXLAN over it
    (node_events.go:184-250 analog routes installed via the shared
    store's node-liveness events),
  * netns "pods" wired by CNI Adds over each agent's unix socket.

Asserts: pod on node A reaches pod on node B (UDP through both device
pipelines + VXLAN encap/decap), a NetworkPolicy published through the
store (KSR key scheme) cuts that traffic off — and after the primary
store CRASHES mid-cluster, the witness-arbitrated failover promotes
the standby, a policy delete lands on the new primary (fenced write),
and cross-node traffic resumes with no agent restarts.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
import urllib.request

import pytest

from vpp_tpu.cni.transport import cni_call
from vpp_tpu.cni.wiring import host_ifname
from vpp_tpu.cmd.config import AgentConfig
from vpp_tpu.cmd.init_main import InitSupervisor
from vpp_tpu.ksr import model as m
from vpp_tpu.kvstore.client import RemoteKVStore


def _can_netns() -> bool:
    try:
        r = subprocess.run(["ip", "netns", "add", "vppt2selfck"],
                           capture_output=True, timeout=10)
        if r.returncode == 0:
            subprocess.run(["ip", "netns", "del", "vppt2selfck"],
                           capture_output=True, timeout=10)
            return True
        return False
    except (OSError, subprocess.TimeoutExpired):
        return False


pytestmark = pytest.mark.skipif(
    not _can_netns(), reason="needs CAP_NET_ADMIN (netns/veth)"
)

RUN = "/tmp/vppt2-run"
FAB = ("vppt2-faba", "vppt2-fabb")
PODS = {"a": "vppt2-poda", "b": "vppt2-podb"}
CIDS = {"a": "aa02" * 5, "b": "bb02" * 5}
KSR_PREFIX = "ksr/"


def sh(*a, **kw):
    return subprocess.run(list(a), capture_output=True, text=True, **kw)


def _cleanup():
    for ns in PODS.values():
        sh("ip", "netns", "del", ns)
    for cid in CIDS.values():
        sh("ip", "link", "del", host_ifname(cid))
    sh("ip", "link", "del", FAB[0])


def _child_env():
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)          # drop the axon plugin
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    env["JAX_PLATFORMS"] = "cpu"
    return env


def _wait_ready(port: int, timeout: float = 120.0) -> None:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/readiness", timeout=2
            ) as r:
                if r.status == 200:
                    return
        except Exception:
            pass
        time.sleep(0.5)
    raise TimeoutError(f"agent on :{port} never became ready")


class Node:
    def __init__(self, name: str, fab_if: str, kv_ports, ports):
        self.name = name
        self.dir = f"{RUN}/{name}"
        os.makedirs(self.dir, exist_ok=True)
        self.cni_socket = f"{self.dir}/cni.sock"
        self.health_port = ports[0]
        store_url = "tcp://" + ",".join(
            f"127.0.0.1:{p}" for p in kv_ports)
        cfg = {
            "node_name": name,
            "store_url": store_url,
            "cni_socket": self.cni_socket,
            "cli_socket": f"{self.dir}/cli.sock",
            "stats_port": ports[1],
            "health_port": ports[0],
            "http_host": "127.0.0.1",
            "io": {
                "enabled": True,
                "shm_name": f"vppt2-{name}",
                "n_slots": 32,
                "snap": 2048,
                "control_socket": f"{self.dir}/io-ctl.sock",
                "uplink_interface": fab_if,
                "plan_path": f"{self.dir}/io-plan.json",
            },
        }
        self.cfg_path = f"{self.dir}/contiv.yaml"
        with open(self.cfg_path, "w") as f:
            json.dump(cfg, f)   # YAML is a JSON superset
        self.agent = None
        self.io = None

    def start(self):
        env = _child_env()
        self._agent_log = open(f"{self.dir}/agent.log", "w")
        self.agent = subprocess.Popen(
            [sys.executable, "-m", "vpp_tpu.cmd.agent",
             "--config", self.cfg_path],
            env=env, stdout=self._agent_log, stderr=subprocess.STDOUT,
        )
        # launch the IO daemon exactly as vpp-tpu-init would
        sup = InitSupervisor(
            AgentConfig.from_dict(json.load(open(self.cfg_path))),
            self.cfg_path, plan_timeout_s=120.0,
        )
        (_, plan), = sup.read_plans().values()
        self._io_log = open(f"{self.dir}/io.log", "w")
        self.io = subprocess.Popen(
            sup.io_argv(plan), env=env,
            stdout=self._io_log, stderr=subprocess.STDOUT,
        )
        from vpp_tpu.io.control import IOControlClient

        ctl = IOControlClient(plan["control_socket"], timeout=3.0)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline and not ctl.ping():
            assert self.io.poll() is None, "io daemon died during startup"
            time.sleep(0.5)
        return self

    def add_pod(self, cid: str, ns: str, pod_name: str) -> str:
        # kubelet-style retry loop: TRY_AGAIN (11) while the vswitch
        # base config / IO daemon comes up
        deadline = time.monotonic() + 90
        while True:
            reply = cni_call(self.cni_socket, "Add", {
                "container_id": cid, "netns": f"/var/run/netns/{ns}",
                "if_name": "eth0",
                "extra_args": {"K8S_POD_NAME": pod_name,
                               "K8S_POD_NAMESPACE": "default"},
            }, timeout=60.0)
            if reply["result"] == 11 and time.monotonic() < deadline:
                time.sleep(1.0)
                continue
            assert reply["result"] == 0, reply
            return reply["interfaces"][0]["ip_addresses"][0][
                "address"].split("/")[0]

    def stop(self):
        for p in (self.io, self.agent):
            if p is not None and p.poll() is None:
                p.terminate()
        for p in (self.io, self.agent):
            if p is not None:
                try:
                    p.wait(timeout=15)
                except subprocess.TimeoutExpired:
                    p.kill()


@pytest.fixture(scope="module")
def cluster():
    import shutil

    _cleanup()
    shutil.rmtree(RUN, ignore_errors=True)  # stale plans/sockets poison
    os.makedirs(RUN, exist_ok=True)         # the boot handshake

    for ns in PODS.values():
        subprocess.run(["ip", "netns", "add", ns], check=True, timeout=10)
    # the inter-node fabric
    subprocess.run(["ip", "link", "add", FAB[0], "type", "veth",
                    "peer", "name", FAB[1]], check=True, timeout=10)
    for f in FAB:
        subprocess.run(["ip", "link", "set", f, "up"], check=True,
                       timeout=10)

    env = _child_env()

    def _port(path, timeout=30):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                return int(open(path).read())
            except (OSError, ValueError):
                time.sleep(0.2)
        raise TimeoutError(path)

    # the fenced store trio, wired exactly as the chart deploys it.
    # fence-ttl is generous for the 1-core host: thread starvation
    # under load must not read as a dead primary mid-test.
    witness = subprocess.Popen(
        [sys.executable, "-m", "vpp_tpu.cmd.kvwitness", "--host",
         "127.0.0.1", "--port", "0", "--port-file", f"{RUN}/w.port"],
        env=env)
    w_port = _port(f"{RUN}/w.port")
    kv = subprocess.Popen(
        [sys.executable, "-m", "vpp_tpu.cmd.kvserver", "--host",
         "127.0.0.1", "--port", "0", "--port-file", f"{RUN}/kv.port",
         "--witness", f"127.0.0.1:{w_port}", "--fence-ttl", "6"],
        env=env)
    kv_port = _port(f"{RUN}/kv.port")
    standby = subprocess.Popen(
        [sys.executable, "-m", "vpp_tpu.cmd.kvserver", "--host",
         "127.0.0.1", "--port", "0", "--port-file", f"{RUN}/sb.port",
         "--follow", f"127.0.0.1:{kv_port}",
         "--witness", f"127.0.0.1:{w_port}",
         "--fence-ttl", "6", "--promote-after", "3"],
        env=env)
    sb_port = _port(f"{RUN}/sb.port", timeout=60)

    node_a = Node("node-a", FAB[0], (kv_port, sb_port),
                  (21191, 21991)).start()
    node_b = Node("node-b", FAB[1], (kv_port, sb_port),
                  (21192, 21992)).start()
    try:
        _wait_ready(node_a.health_port)
        _wait_ready(node_b.health_port)
        yield {"a": node_a, "b": node_b, "kv_port": kv_port,
               "sb_port": sb_port, "w_port": w_port,
               "kv_proc": kv}
    finally:
        for n in (node_a, node_b):
            try:
                n.stop()
            except Exception:
                pass
        for p in (standby, kv, witness):
            if p.poll() is None:
                p.terminate()
        for p in (standby, kv, witness):
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        _cleanup()


def _udp_recv(ns: str, port: int, timeout_s: int = 60):
    return subprocess.Popen(
        ["ip", "netns", "exec", ns, sys.executable, "-c",
         "import socket\ns=socket.socket(socket.AF_INET,socket.SOCK_DGRAM)\n"
         f"s.bind(('0.0.0.0', {port}))\ns.settimeout({timeout_s})\n"
         "d,p=s.recvfrom(4096)\nprint(d.decode()+'|'+p[0], flush=True)\n"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )


def _udp_spray(ns: str, dst: str, port: int, msg: str, times: int,
               gap: float = 0.25):
    subprocess.run(
        ["ip", "netns", "exec", ns, sys.executable, "-c",
         "import socket,time\n"
         "s=socket.socket(socket.AF_INET,socket.SOCK_DGRAM)\n"
         f"for _ in range({times}):\n"
         f"    s.sendto({msg!r}.encode(), ('{dst}', {port}))\n"
         f"    time.sleep({gap})\n"],
        timeout=times * gap + 30, capture_output=True, check=True,
    )


# slow: ~2 min of subprocess boots, netns traffic and failover polling
# — the single largest tier-1 line item, and the two cases are a
# SEQUENCE (the failover case un-blocks the policy the first case
# cut), so they move to the slow tier together. The mesh suite now
# RUNS on this toolchain (ISSUE 12 un-skipped ~20 tests), and the
# `-m 'not slow'` budget can't absorb both; fenced-store failover
# stays covered in tier-1 by test_kvstore_fencing, cross-node wire by
# test_mesh_wire_e2e/test_proxy_chain_e2e.
@pytest.mark.slow
class TestTwoNodeTwoPods:
    def test_cross_node_udp_then_policy_cutoff(self, cluster):
        a, b = cluster["a"], cluster["b"]
        ip_a = a.add_pod(CIDS["a"], PODS["a"], "pod-a")
        ip_b = b.add_pod(CIDS["b"], PODS["b"], "pod-b")
        # different nodes -> different /24s of the pod supernet
        assert ip_a.split(".")[2] != ip_b.split(".")[2]

        # pod A (node A) -> pod B (node B): crosses both pipelines and
        # the VXLAN fabric. Generous spray: first packets pay each
        # side's jit compile.
        recv = _udp_recv(PODS["b"], 6011, timeout_s=110)
        time.sleep(0.5)
        _udp_spray(PODS["a"], ip_b, 6011, "cross-node-hello", times=400)
        out, err = recv.communicate(timeout=120)
        assert "cross-node-hello" in out, (out, err)
        assert ip_a in out

        # ClusterIP service leg (robot suite's service case): a VIP on
        # UDP/5300 backed by pod-b on the OTHER node. Pod A sends to
        # the VIP; node A's NAT44 DNATs to pod-b and the flow rides the
        # VXLAN fabric to node B.
        cli = RemoteKVStore("127.0.0.1", cluster["kv_port"])
        try:
            svc = m.Service(
                name="svc-b", namespace="default",
                cluster_ip="10.96.0.50",
                ports=[m.ServicePort(name="u", protocol="UDP", port=5300,
                                     target_port=6013)],
                selector={"app": "b"},
            )
            eps = m.Endpoints(
                name="svc-b", namespace="default",
                subsets=[m.EndpointSubset(
                    addresses=[m.EndpointAddress(
                        ip=ip_b, node_name="node-b",
                        target_pod="default/pod-b")],
                    ports=[m.EndpointPort(name="u", port=6013,
                                          protocol="UDP")],
                )],
            )
            cli.put(KSR_PREFIX + svc.key(), svc.to_dict())
            cli.put(KSR_PREFIX + eps.key(), eps.to_dict())
            deadline = time.monotonic() + 60
            got_vip = False
            while time.monotonic() < deadline and not got_vip:
                recv_svc = _udp_recv(PODS["b"], 6013, timeout_s=8)
                time.sleep(0.3)
                try:
                    _udp_spray(PODS["a"], "10.96.0.50", 5300,
                               "via-the-vip", times=16)
                except subprocess.CalledProcessError:
                    pass
                out_svc, _ = recv_svc.communicate(timeout=30)
                got_vip = "via-the-vip" in (out_svc or "")
            assert got_vip, "ClusterIP DNAT across nodes never delivered"

            # NetworkPolicy via the store (KSR key scheme): pod-b accepts
            # only TCP/9 -> the UDP flows must die in node B's classifier
            pod_a = m.Pod(name="pod-a", namespace="default",
                          labels={"app": "a"}, ip_address=ip_a)
            pod_b = m.Pod(name="pod-b", namespace="default",
                          labels={"app": "b"}, ip_address=ip_b)
            cli.put(KSR_PREFIX + pod_a.key(), pod_a.to_dict())
            cli.put(KSR_PREFIX + pod_b.key(), pod_b.to_dict())
            pol = m.Policy(
                name="lock-b", namespace="default",
                pods=m.LabelSelector(match_labels={"app": "b"}),
                policy_type=m.POLICY_INGRESS,
                ingress_rules=[m.PolicyRule(
                    ports=[m.PolicyPort(protocol="TCP", port=9)],
                    peers=[],
                )],
            )
            cli.put(KSR_PREFIX + pol.key(), pol.to_dict())

            # wait for the render to land, then verify the cutoff
            deadline = time.monotonic() + 60
            blocked = False
            while time.monotonic() < deadline and not blocked:
                recv2 = _udp_recv(PODS["b"], 6012, timeout_s=6)
                time.sleep(0.3)
                try:
                    _udp_spray(PODS["a"], ip_b, 6012, "blocked?", times=12)
                except subprocess.CalledProcessError:
                    pass
                out2, _ = recv2.communicate(timeout=30)
                blocked = "blocked?" not in (out2 or "")
            assert blocked, "policy never cut cross-node traffic"
        finally:
            cli.close()

    def test_store_failover_keeps_cluster_serving(self, cluster):
        """The primary store CRASHES under the live cluster (the
        etcd-pod-death case the reference rides Kubernetes restarts
        for): the witness grants the standby's claim, both agents fail
        over (watch resync, fenced writes at the bumped epoch), a
        policy DELETE through the new primary un-blocks the cross-node
        traffic the previous test cut — the whole control loop keeps
        working with no agent or daemon restarts."""
        import signal

        from vpp_tpu.kvstore.witness import WitnessClient

        cluster["kv_proc"].send_signal(signal.SIGKILL)
        cluster["kv_proc"].wait(timeout=15)

        # witness-arbitrated promotion: the standby is the new primary
        wc = WitnessClient(f"127.0.0.1:{cluster['w_port']}")
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            st = wc.status()
            if st["primary"] == f"127.0.0.1:{cluster['sb_port']}" \
                    and st["epoch"] >= 1:
                break
            time.sleep(0.5)
        else:
            raise AssertionError(f"standby never promoted: {wc.status()}")

        # a fenced write through the failed-over client: deleting the
        # lock-b policy must re-open pod-b (renderer unwind on BOTH
        # nodes, driven entirely by the new primary's watch stream)
        cli = RemoteKVStore(
            "127.0.0.1", cluster["kv_port"], request_timeout=20.0,
            reconnect_timeout=30.0,
            fallbacks=[("127.0.0.1", cluster["sb_port"])])
        try:
            pol_key = KSR_PREFIX + m.Policy(
                name="lock-b", namespace="default").key()
            assert cli.get(pol_key) is not None, \
                "expected the previous test's policy in the store"
            assert cli.delete(pol_key) is True
            assert cli.fencing_epoch >= 1

            # pod-b's IP from the store (survived the failover via
            # replication)
            pod_b = cli.get(KSR_PREFIX + m.Pod(
                name="pod-b", namespace="default").key())
            ip_b = pod_b["ip_address"]

            deadline = time.monotonic() + 90
            flowing = False
            while time.monotonic() < deadline and not flowing:
                recv3 = _udp_recv(PODS["b"], 6014, timeout_s=6)
                time.sleep(0.3)
                try:
                    _udp_spray(PODS["a"], ip_b, 6014,
                               "after-failover", times=12)
                except subprocess.CalledProcessError:
                    pass
                out3, _ = recv3.communicate(timeout=30)
                flowing = "after-failover" in (out3 or "")
            assert flowing, (
                "cross-node traffic never resumed after the store "
                "failover + policy delete")
        finally:
            cli.close()
