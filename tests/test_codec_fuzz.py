"""Fuzz the native wire codec: arbitrary bytes from the network must
never crash the parser, claim more captured bytes than exist, or leave
a transmittable frame whose length lies (the trunc-flag discipline the
tx path's no-cross-flow-leak guarantee rests on)."""

from __future__ import annotations

import numpy as np
from hypothesis import given, settings
from hypothesis import strategies as st

from vpp_tpu.io.rings import VEC
from vpp_tpu.native.pktio import (
    FLAG_NON_IP4,
    FLAG_TRUNC,
    FLAG_VALID,
    PacketCodec,
)

SNAP = 256


@st.composite
def frame_batches(draw):
    rng = np.random.default_rng(draw(st.integers(0, 2**32 - 1)))
    n = draw(st.integers(1, 64))
    frames = []
    for _ in range(n):
        kind = rng.integers(0, 4)
        if kind == 0:                      # pure noise
            length = int(rng.integers(0, 400))
            frames.append(rng.integers(0, 256, length, np.uint8)
                          .tobytes())
        elif kind == 1:                    # IPv4 ethertype, noisy header
            length = int(rng.integers(14, 400))
            b = bytearray(rng.integers(0, 256, length, np.uint8)
                          .tobytes())
            b[12:14] = b"\x08\x00"
            frames.append(bytes(b))
        elif kind == 2:                    # valid-ish IPv4, lying length
            import struct

            claimed = int(rng.integers(0, 65535))
            ihl = int(rng.integers(0, 16))
            payload = rng.integers(0, 256, int(rng.integers(0, 120)),
                                   np.uint8).tobytes()
            hdr = struct.pack(
                "!BBHHHBBH4s4s", 0x40 | ihl, 0, claimed, 0, 0, 64,
                int(rng.integers(0, 255)), 0, b"\x0a\x01\x01\x02",
                b"\x0a\x01\x01\x03")
            frames.append(b"\x02" * 12 + b"\x08\x00" + hdr + payload)
        else:                              # VXLAN-ish datagram
            inner = rng.integers(0, 256, int(rng.integers(0, 80)),
                                 np.uint8).tobytes()
            frames.append(b"\x02" * 12 + b"\x08\x00"
                          + bytes(rng.integers(0, 256, 28, np.uint8))
                          + b"\x08\x00\x00\x00" + b"\x00\x00\x0a\x00"
                          + inner)
    return frames


@given(frame_batches())
@settings(max_examples=80, deadline=None)
def test_parse_never_unsafe(frames):
    codec = PacketCodec(snap=SNAP)
    scratch = np.zeros((VEC, SNAP), np.uint8)
    cols, n = codec.parse(frames, 1, scratch)
    assert n == min(len(frames), VEC)
    flags = cols["flags"][:n]
    pkt_len = cols["pkt_len"][:n]
    for i in range(n):
        f, length = int(flags[i]), int(pkt_len[i])
        assert f & FLAG_VALID
        captured = min(len(frames[i]), SNAP)
        if not f & FLAG_TRUNC:
            # a transmittable slot's wire length must be covered by
            # captured bytes — anything else leaks stale slot data
            assert length + 14 <= max(captured, 14), (i, length, captured)
        if not f & FLAG_NON_IP4:
            assert 0 <= length <= 65535
    # rewrite over fuzzed columns must not crash either
    codec.rewrite(cols, scratch, n)


@given(frame_batches())
@settings(max_examples=40, deadline=None)
def test_decap_batch_never_unsafe(frames):
    codec = PacketCodec(snap=SNAP)
    scratch = np.zeros((VEC, SNAP), np.uint8)
    lens = np.zeros(VEC, np.uint32)
    n = min(len(frames), VEC)
    for i in range(n):
        b = frames[i][:SNAP]
        scratch[i, :len(b)] = np.frombuffer(b, np.uint8)
        lens[i] = len(frames[i])  # true wire length (may exceed snap)
    codec.decap_batch(scratch, lens, n, 10)
    # decap may only shrink, never grow past the captured bytes
    for i in range(n):
        assert lens[i] <= max(len(frames[i]), 0)
