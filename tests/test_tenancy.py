"""Multi-tenant gateway mode (ISSUE 14; vpp_tpu/tenancy/).

Four layers:

* **device kernels** — tenant-id derivation (first-match-wins,
  symmetric under src/dst swap) and the per-tenant token bucket pinned
  against an INDEPENDENT NumPy oracle over seeded multi-window traffic
  (refill clamp, burst cap, in-batch arrival-rank determinism,
  rate=0 unlimited).
* **pipeline differentials** — quota drops attributed DROP_TENANT with
  exact conservation and no session install; tenancy-on-unconfigured
  bit-exact vs tenancy-off (the default staging is the identity);
  tenant-sliced session capacity where a flooded slice fails/evicts
  only WITHIN its owning tenant (never cross-tenant — structural);
  replies landing in the same slice (the symmetric-key contract);
  per-tenant ML mode/threshold overrides against ONE staged model with
  zero weight re-ship; the tenant upload group's independence from
  rule churn; and the shard-composition differential (tenant-sliced
  bucket indices under the 2-way mesh ownership split reproduce the
  standalone lookup bit-exactly — the PARTITION_RULES contract).
* **host scheduling** — TenantScheduler WFQ units (proportional
  service, idle-rebase anti-banking, hog-first shedding, ring-fault
  requeue) + TenantClassifier units, then the REAL pump: a saturating
  tenant's backlog cannot starve a later-arriving light tenant
  (weighted-fair dequeue), and the device token-bucket drops surface
  as drops_tenant_quota with the per-tenant planes agreeing exactly.
* **wiring** — config validation refusals at YAML load, `show
  tenants`, the vpp_tpu_tenant_* families, the one-new-step-form +
  zero-io_callback contract.
"""

from __future__ import annotations

import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from wire import make_frame

from vpp_tpu.ir.rule import Action, ContivRule, Protocol
from vpp_tpu.io import DataplanePump, IORingPair
from vpp_tpu.native.pktio import PacketCodec
from vpp_tpu.pipeline.dataplane import Dataplane
from vpp_tpu.pipeline.graph import DROP_TENANT
from vpp_tpu.pipeline.tables import (
    SESSION_FIELDS,
    DataplaneConfig,
)
from vpp_tpu.pipeline.vector import (
    VEC,
    Disposition,
    ip4,
    make_packet_vector,
)
from vpp_tpu.tenancy.derive import tenant_ids, tenant_limit
from vpp_tpu.tenancy.sched import (
    TenantClassifier,
    TenantScheduler,
    tenant_entries_from_config,
    validate_tenancy_config,
)
from vpp_tpu.testing import faults

# tenant address plan: tenant 1 owns 10.50/16, tenant 2 owns 10.60/16,
# everything else (10.1.1.0/24 pods) is the default tenant 0
T1_NET = "10.50.0.0/16"
T2_NET = "10.60.0.0/16"


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.install(None)
    yield
    faults.install(None)


def build_dp(tenants=(), sess_slots=256, **over):
    """Tenancy-on dataplane: pod route for 10.1.1.0/24, default-route
    uplink, permit-TCP-80 + permit-UDP + deny global table, the given
    tenant registry staged before the first swap."""
    cfg = DataplaneConfig(
        max_tables=2, max_rules=16, max_global_rules=32, max_ifaces=8,
        fib_slots=16, sess_slots=sess_slots, nat_mappings=2,
        nat_backends=2, tenancy="on", sess_sweep_stride=0, **over,
    )
    dp = Dataplane(cfg)
    up = dp.add_uplink()
    pod = dp.add_pod_interface(("default", "web"))
    dp.builder.add_route("10.1.1.0/24", pod, Disposition.LOCAL)
    dp.builder.add_route("0.0.0.0/0", up, Disposition.REMOTE, node_id=1)
    dp.builder.set_global_table([
        ContivRule(action=Action.PERMIT, protocol=Protocol.TCP,
                   dest_port=80),
        ContivRule(action=Action.PERMIT, protocol=Protocol.UDP),
        ContivRule(action=Action.DENY),
    ])
    for e in tenants:
        kw = {k: v for k, v in e.items() if k != "id"}
        dp.builder.set_tenant(e["id"], **kw)
    dp.swap()
    return dp, up, pod


def tenant_traffic(up, tid_nets, n=None, seed=0, dport=80, proto=6):
    """One packet per (net, i) pair: src inside the tenant's net,
    dst a pod address — distinct flows per call via the seed."""
    rng = np.random.default_rng(seed)
    pkts = []
    for net, count in tid_nets:
        base = net.split("/")[0].rsplit(".", 2)[0]
        for i in range(count):
            pkts.append(dict(
                src=f"{base}.{rng.integers(0, 250)}.{rng.integers(1, 250)}",
                dst=f"10.1.1.{2 + (i % 200)}",
                proto=proto, sport=int(rng.integers(1024, 65000)),
                dport=dport, rx_if=up))
    return make_packet_vector(pkts, n=n or max(16, len(pkts)))


# --------------------------------------------------------------------
# derivation + token bucket vs the NumPy oracle
# --------------------------------------------------------------------


class TestDerivation:
    def test_derivation_multi_prefix_and_default(self):
        """Disjoint prefix ownership (cross-tenant overlap is refused
        at validation — the device's first-match and the host
        classifier's max only agree on disjoint maps): a tenant may
        hold several prefixes, including SAME-tenant nesting, and
        unmatched addresses derive the default tenant 0."""
        dp, up, _pod = build_dp(tenants=[
            # same-tenant nesting is harmless: either slot derives 2
            {"id": 2, "prefixes": ["10.50.7.0/24", T1_NET]},
            {"id": 3, "prefixes": [T2_NET]},
        ])
        pv = make_packet_vector([
            {"src": "10.50.7.9", "dst": "10.1.1.2", "proto": 6,
             "sport": 1, "dport": 80, "rx_if": up},   # nested -> 2
            {"src": "10.50.8.9", "dst": "10.1.1.2", "proto": 6,
             "sport": 2, "dport": 80, "rx_if": up},   # broad -> 2
            {"src": "10.60.0.9", "dst": "10.1.1.2", "proto": 6,
             "sport": 3, "dport": 80, "rx_if": up},   # -> 3
            {"src": "172.16.0.1", "dst": "10.1.1.2", "proto": 6,
             "sport": 4, "dport": 80, "rx_if": up},   # unmatched -> 0
        ], n=8)
        tid = np.asarray(tenant_ids(dp.tables, pv))
        assert tid[0] == 2 and tid[1] == 2 and tid[2] == 3 \
            and tid[3] == 0

    def test_symmetric_under_src_dst_swap(self):
        """key_tenant(a, b) == key_tenant(b, a) — the property that
        makes a forward flow's insert key and the reply's lookup key
        land in the same tenant slice."""
        dp, up, _pod = build_dp(tenants=[
            {"id": 1, "prefixes": [T1_NET]},
            {"id": 3, "prefixes": [T2_NET]},
        ])
        rng = np.random.default_rng(7)
        fwd = make_packet_vector([
            dict(src=f"10.{rng.choice([50, 60, 1])}.{rng.integers(0, 250)}"
                     f".{rng.integers(1, 250)}",
                 dst=f"10.{rng.choice([50, 60, 1])}.{rng.integers(0, 250)}"
                     f".{rng.integers(1, 250)}",
                 proto=6, sport=100 + i, dport=80, rx_if=up)
            for i in range(24)
        ], n=24)
        rev = make_packet_vector([
            dict(src=".".join(str((int(np.asarray(fwd.dst_ip)[i]) >> s)
                                  & 255) for s in (24, 16, 8, 0)),
                 dst=".".join(str((int(np.asarray(fwd.src_ip)[i]) >> s)
                                  & 255) for s in (24, 16, 8, 0)),
                 proto=6, sport=80, dport=100 + i, rx_if=up)
            for i in range(24)
        ], n=24)
        assert np.array_equal(np.asarray(tenant_ids(dp.tables, fwd)),
                              np.asarray(tenant_ids(dp.tables, rev)))


def bucket_oracle(rate, burst, tokens, tok_time, tids, alive, now):
    """Independent sequential re-implementation of tenancy/derive.py
    tenant_limit: per-packet, in packet order, each tenant consumes
    from its refilled bucket."""
    T = len(rate)
    dt = np.clip(now - tok_time, 0, 1 << 14)
    tok = np.minimum(burst, tokens + rate * dt).astype(np.int64)
    dropped = np.zeros(len(tids), bool)
    for p in range(len(tids)):
        if not alive[p]:
            continue
        t = tids[p]
        if rate[t] <= 0:
            continue
        if tok[t] > 0:
            tok[t] -= 1
        else:
            dropped[p] = True
    tok_after = np.where(rate > 0, np.clip(tok, 0, burst), burst)
    return dropped, tok_after.astype(np.int32), \
        np.full(T, now, np.int32)


class TestTokenBucketOracle:
    def test_multi_window_differential(self):
        """Seeded mixed traffic over 3 tenants x 6 windows with
        varying inter-window gaps (including a clamp-sized idle gap):
        dropped mask and bucket levels bit-equal to the sequential
        oracle every window."""
        dp, up, _pod = build_dp(tenants=[
            {"id": 1, "prefixes": [T1_NET], "rate": 3, "burst": 8},
            {"id": 2, "prefixes": [T2_NET], "rate": 1, "burst": 2},
            # tenant 3: registered but unlimited (rate 0)
            {"id": 3, "prefixes": ["10.70.0.0/16"], "rate": 0},
        ])
        tables = dp.tables
        rng = np.random.default_rng(11)
        now = 5
        for w, gap in enumerate((0, 1, 2, 7, 40000, 1)):
            now += gap
            pv = tenant_traffic(
                up, [(T1_NET, int(rng.integers(2, 12))),
                     (T2_NET, int(rng.integers(1, 6))),
                     ("10.70.0.0/16", 3),
                     ("172.16.0.0/16", 2)],
                n=32, seed=100 + w)
            alive = np.asarray(pv.valid)
            tids = np.asarray(tenant_ids(tables, pv))
            want_drop, want_tok, want_time = bucket_oracle(
                np.asarray(tables.tnt_rate),
                np.asarray(tables.tnt_burst),
                np.asarray(tables.tnt_tokens),
                np.asarray(tables.tnt_tok_time),
                tids, alive, now)
            tables, dropped = tenant_limit(
                tables, jnp.asarray(tids), jnp.asarray(alive),
                jnp.int32(now))
            assert np.array_equal(np.asarray(dropped), want_drop), \
                f"window {w}: dropped mask diverged"
            assert np.array_equal(np.asarray(tables.tnt_tokens),
                                  want_tok), f"window {w}: levels"
            assert np.array_equal(np.asarray(tables.tnt_tok_time),
                                  want_time)
        # the schedule really exercised both outcomes
        assert int(np.asarray(tables.tnt_tokens)[2]) >= 0

    def test_refill_no_int32_overflow_at_bounds(self):
        """rate=2^16 with burst=2^30 (both at the validator's
        inclusive bounds) and clamp-sized idle gaps: the naive
        ``tokens + rate*dt`` reaches exactly 2^31 and wraps negative —
        the headroom-capped refill must keep a full bucket at burst
        and keep admitting in-quota traffic."""
        dp, up, _pod = build_dp(tenants=[
            {"id": 1, "prefixes": [T1_NET], "rate": 1 << 16,
             "burst": 1 << 30},
        ])
        tables = dp.tables
        none = jnp.zeros(16, jnp.int32), jnp.zeros(16, bool)
        # prime: one empty round at a clamp-sized gap fills the bucket
        # to burst (rate*dt alone == 2^30)
        tables, _ = tenant_limit(tables, none[0], none[1],
                                 jnp.int32(1 << 14))
        assert int(np.asarray(tables.tnt_tokens)[1]) == 1 << 30
        # second clamp-sized idle gap with the bucket FULL: the naive
        # sum is 2^31 (negative in int32) and would drop everything
        pv = tenant_traffic(up, [(T1_NET, 8)], n=16, seed=3)
        tids = jnp.asarray(np.asarray(tenant_ids(tables, pv)))
        alive = jnp.asarray(np.asarray(pv.valid))
        tables, dropped = tenant_limit(tables, tids, alive,
                                       jnp.int32(2 << 14))
        assert not np.asarray(dropped).any(), \
            "int32 refill overflow dropped in-quota traffic"
        assert int(np.asarray(tables.tnt_tokens)[1]) == (1 << 30) - 8


# --------------------------------------------------------------------
# pipeline differentials
# --------------------------------------------------------------------


class TestQuotaDrops:
    def test_attributed_conserved_and_no_session(self):
        """Over-quota packets: DROP_TENANT attribution, StepStats
        conservation (rx counts them, tx excludes them), and NO
        session install for a dropped packet."""
        dp, up, _pod = build_dp(tenants=[
            {"id": 1, "prefixes": [T1_NET], "rate": 1, "burst": 4},
        ])
        pv = tenant_traffic(up, [(T1_NET, 10), ("172.16.0.0/16", 3)],
                            n=16, seed=1)
        # now=100 lets the bucket refill to burst (rate 1/tick from
        # the zero init at tick 0)
        res = dp.process(pv, now=100)
        limited = int(res.stats.tnt_limited)
        assert limited == 6  # burst 4 admits 4 of 10; 3 default free
        cause = np.asarray(res.drop_cause)
        assert (cause == DROP_TENANT).sum() == limited
        # conservation: rx counts the dropped packets as received
        assert int(res.stats.rx) == 13
        assert int(res.stats.tx) + int(res.stats.drop_acl) \
            + limited == 13
        # dropped packets installed no session: only the 7 forwarded
        # flows are resident
        assert int(np.asarray(dp.tables.sess_valid).sum()) == 7
        snap = dp.tenant_snapshot()
        assert snap is not None
        assert int(snap["rl_drops"][1]) == limited
        assert int(snap["rx"][1]) == 10
        assert int(snap["tx"][1]) + limited == 10

    def test_unconfigured_tenancy_is_bit_exact_identity(self):
        """tenancy: on with NO tenants registered must forward
        bit-identically to tenancy: off — same verdicts, same session
        cells (the default staging hashes into the same buckets)."""
        dp_on, up, _ = build_dp()
        cfg_off = DataplaneConfig(
            max_tables=2, max_rules=16, max_global_rules=32,
            max_ifaces=8, fib_slots=16, sess_slots=256, nat_mappings=2,
            nat_backends=2, tenancy="off", sess_sweep_stride=0)
        dp_off = Dataplane(cfg_off)
        up2 = dp_off.add_uplink()
        pod2 = dp_off.add_pod_interface(("default", "web"))
        dp_off.builder.add_route("10.1.1.0/24", pod2, Disposition.LOCAL)
        dp_off.builder.add_route("0.0.0.0/0", up2, Disposition.REMOTE,
                                 node_id=1)
        dp_off.builder.set_global_table([
            ContivRule(action=Action.PERMIT, protocol=Protocol.TCP,
                       dest_port=80),
            ContivRule(action=Action.PERMIT, protocol=Protocol.UDP),
            ContivRule(action=Action.DENY),
        ])
        dp_off.swap()
        assert up == up2
        for step, seed in ((1, 3), (2, 3), (3, 4)):  # repeat = refresh
            pv = tenant_traffic(up, [(T1_NET, 6), (T2_NET, 4),
                                     ("172.16.0.0/16", 4)],
                                n=16, seed=seed)
            ra = dp_on.process(pv, now=step)
            rb = dp_off.process(pv, now=step)
            for f in ("disp", "tx_if", "drop_cause", "established"):
                assert np.array_equal(np.asarray(getattr(ra, f)),
                                      np.asarray(getattr(rb, f))), f
            for f in SESSION_FIELDS:
                assert np.array_equal(
                    np.asarray(getattr(ra.tables, f)),
                    np.asarray(getattr(rb.tables, f))), \
                    f"{f} diverged — default staging is not identity"


class TestSlicedCapacity:
    def _sliced_pair(self):
        # 256 slots / 4 ways = 64 buckets; tenant 1+2 sliced 4 buckets
        # (16 slots) each
        return build_dp(tenants=[
            {"id": 1, "prefixes": [T1_NET], "sess_buckets": 4},
            {"id": 2, "prefixes": [T2_NET], "sess_buckets": 4},
        ])

    def test_flood_never_evicts_other_tenant(self):
        """Fill tenant 2 with 8 flows, then flood tenant 1 with 64
        distinct flows into its 16-slot slice: tenant 1 over-fills
        (insert failures counted against IT), tenant 2's sessions all
        survive — structurally untouchable by the flood."""
        dp, up, _pod = self._sliced_pair()
        r0 = dp.process(
            tenant_traffic(up, [(T2_NET, 8)], n=16, seed=5), now=1)
        assert int(r0.stats.tx) == 8
        snap = dp.tenant_snapshot()
        t2_live = int(snap["occupancy"][2])
        # 8 flows over 16 slice slots: a same-bucket overflow is
        # possible but most must land
        assert t2_live >= 6
        # the flood: 64 distinct UDP flows in one batch
        r1 = dp.process(
            tenant_traffic(up, [(T1_NET, 64)], n=64, seed=6,
                           dport=5000, proto=17), now=2)
        snap = dp.tenant_snapshot()
        assert int(snap["occupancy"][1]) <= 16  # capped at the slice
        assert int(snap["occupancy"][2]) == t2_live  # UNTOUCHED
        # over-filling a 16-slot slice with 64 same-batch flows MUST
        # fail some inserts, attributed to tenant 1
        assert int(r1.stats.tnt_qfail) > 0
        assert int(snap["quota_fails"][1]) == int(r1.stats.tnt_qfail)
        assert int(snap["quota_fails"][2]) == 0

    def test_unsliced_flood_never_evicts_sliced_tenant(self):
        """The REVERSE direction of the no-eviction guarantee: default
        (unmatched → tenant 0, unsliced) flood traffic hashes only
        into the residual bottom region — slices allocate from the top
        of the table, so an unsliced flood is structurally unable to
        touch a sliced tenant's residents."""
        dp, up, _pod = self._sliced_pair()
        r0 = dp.process(
            tenant_traffic(up, [(T2_NET, 8)], n=16, seed=5), now=1)
        assert int(r0.stats.tx) == 8
        snap = dp.tenant_snapshot()
        t2_live = int(snap["occupancy"][2])
        assert t2_live >= 6
        # the flood arrives from an UNREGISTERED range: 64 distinct
        # UDP flows derive tenant 0 and contend only with each other
        dp.process(
            tenant_traffic(up, [("172.16.0.0/16", 64)], n=64, seed=9,
                           dport=5000, proto=17), now=2)
        snap = dp.tenant_snapshot()
        assert int(snap["occupancy"][2]) == t2_live, \
            "unsliced flood evicted a sliced tenant's sessions"
        assert int(snap["quota_fails"][2]) == 0

    def test_reply_lands_in_same_slice_established(self):
        """The symmetric-key contract end-to-end: forward flows from a
        SLICED tenant install sessions; their replies (reversed
        endpoints) hit established — the reverse lookup hashed into
        the same slice."""
        dp, up, pod = self._sliced_pair()
        fwd = tenant_traffic(up, [(T1_NET, 6)], n=16, seed=8)
        r0 = dp.process(fwd, now=1)
        assert int(r0.stats.tx) == 6
        reply = make_packet_vector([
            dict(src=".".join(str((int(np.asarray(fwd.dst_ip)[i]) >> s)
                                  & 255) for s in (24, 16, 8, 0)),
                 dst=".".join(str((int(np.asarray(fwd.src_ip)[i]) >> s)
                                  & 255) for s in (24, 16, 8, 0)),
                 proto=6, sport=80,
                 dport=int(np.asarray(fwd.sport)[i]), rx_if=pod)
            for i in range(6)
        ], n=16)
        r1 = dp.process(reply, now=2)
        est = np.asarray(r1.established)
        assert est[:6].all(), "reply missed its own tenant slice"


class TestTenantMl:
    def _ml_dp(self, tenants):
        from vpp_tpu.ml.train import train_and_pack

        model, _ = train_and_pack(kind="mlp", hidden=8, seed=7,
                                  action="drop")
        cfg = DataplaneConfig(
            max_tables=2, max_rules=16, max_global_rules=32,
            max_ifaces=8, fib_slots=16, sess_slots=256, nat_mappings=2,
            nat_backends=2, tenancy="on", sess_sweep_stride=0,
            ml_stage="enforce", ml_hidden=8)
        dp = Dataplane(cfg)
        up = dp.add_uplink()
        pod = dp.add_pod_interface(("default", "web"))
        dp.builder.add_route("10.1.1.0/24", pod, Disposition.LOCAL)
        dp.builder.add_route("0.0.0.0/0", up, Disposition.REMOTE,
                             node_id=1)
        dp.builder.set_global_table([ContivRule(action=Action.PERMIT)])
        model.flag_thresh = -(1 << 30)  # flag EVERYTHING (inherit)
        dp.builder.set_ml_model(model)
        for e in tenants:
            kw = {k: v for k, v in e.items() if k != "id"}
            dp.builder.set_tenant(e["id"], **kw)
        dp.swap()
        return dp, up

    def test_per_tenant_modes_against_one_model(self):
        """One staged flag-everything drop model; tenant 1 ml off,
        tenant 2 score-only, tenant 3 a never-flag threshold override,
        default inherits enforce: per-packet outcomes follow the
        TENANT, not the global stage."""
        dp, up = self._ml_dp([
            {"id": 1, "prefixes": [T1_NET], "ml_mode": "off"},
            {"id": 2, "prefixes": [T2_NET], "ml_mode": "score"},
            {"id": 3, "prefixes": ["10.70.0.0/16"],
             "ml_thresh": (1 << 31) - 1},
        ])
        pv = tenant_traffic(
            up, [(T1_NET, 4), (T2_NET, 4), ("10.70.0.0/16", 4),
                 ("172.16.0.0/16", 4)], n=16, seed=9)
        res = dp.process(pv, now=1)
        tid = np.asarray(tenant_ids(dp.tables, pv))
        disp = np.asarray(res.disp)
        cause = np.asarray(res.drop_cause)
        from vpp_tpu.pipeline.graph import DROP_ML
        from vpp_tpu.pipeline.vector import Disposition as D

        fwd = disp == int(D.LOCAL)
        # tenant 1 (ml off) + tenant 3 (thresh max): all forwarded
        assert fwd[tid == 1].all()
        assert fwd[tid == 3].all()
        # tenant 2 (score): flagged but never dropped
        assert fwd[tid == 2].all()
        # default tenant inherits enforce: all ml-dropped
        assert (cause[(tid == 0) & np.asarray(pv.valid)]
                == DROP_ML).all()
        assert int(res.stats.ml_drops) == 4

    def test_threshold_flip_reships_zero_weight_planes(self):
        dp, up = self._ml_dp([
            {"id": 1, "prefixes": [T1_NET]},
        ])
        w1 = dp.tables.glb_ml_w1
        before_pfx = dp.tables.tnt_pfx_net
        with dp.commit_lock:
            dp.builder.set_tenant_ml(1, ml_mode="score",
                                     ml_thresh=123)
            dp.swap()
        assert dp.tables.glb_ml_w1 is w1, \
            "tenant ML flip re-shipped the model planes"
        assert int(np.asarray(dp.tables.glb_ml_tnt_thresh)[1]) == 123
        assert dp.tables.tnt_pfx_net is not before_pfx


class TestUploadGroups:
    def test_tenant_group_independent_of_rule_churn(self):
        dp, up, _pod = build_dp(tenants=[
            {"id": 1, "prefixes": [T1_NET], "rate": 5, "burst": 10},
        ])
        pfx = dp.tables.tnt_pfx_net
        rate = dp.tables.tnt_rate
        rules_before = dp.tables.glb_src_net
        # rule churn: tenant planes ride by identity
        with dp.commit_lock:
            dp.builder.set_global_table([
                ContivRule(action=Action.DENY, protocol=Protocol.TCP,
                           dest_port=2222),
                ContivRule(action=Action.PERMIT)])
            dp.swap()
        assert dp.tables.tnt_pfx_net is pfx
        assert dp.tables.tnt_rate is rate
        # tenant churn: rule planes ride by identity
        rules_now = dp.tables.glb_src_net
        assert rules_now is not rules_before
        with dp.commit_lock:
            dp.builder.set_tenant(2, prefixes=[T2_NET], rate=1,
                                  burst=2)
            dp.swap()
        assert dp.tables.glb_src_net is rules_now
        assert dp.tables.tnt_rate is not rate

    def test_bucket_state_carries_across_swaps(self):
        """Token-bucket levels and accounting planes ride epoch swaps
        by reference — a rule churn must not refill buckets or zero
        counters."""
        dp, up, _pod = build_dp(tenants=[
            {"id": 1, "prefixes": [T1_NET], "rate": 1, "burst": 4},
        ])
        dp.process(tenant_traffic(up, [(T1_NET, 10)], n=16, seed=12),
                   now=1)
        rl_before = int(np.asarray(dp.tables.tnt_rl_c)[1])
        tok_before = int(np.asarray(dp.tables.tnt_tokens)[1])
        assert rl_before > 0
        with dp.commit_lock:
            dp.builder.set_global_table([
                ContivRule(action=Action.PERMIT)])
            dp.swap()
        assert int(np.asarray(dp.tables.tnt_rl_c)[1]) == rl_before
        assert int(np.asarray(dp.tables.tnt_tokens)[1]) == tok_before


class TestShardComposition:
    @pytest.mark.slow  # ~17 s: mesh-sliced tenant lookup compile; per-tenant correctness stays fast, mesh slicing anchored by test_partition 2-way
    def test_sliced_lookup_2way_mesh_bitexact(self):
        """The PARTITION_RULES contract (ISSUE 14): tenant slices
        address GLOBAL bucket units, so the mesh's blocked bucket
        ownership composes unchanged — a 2-way shard_map reverse
        lookup over a tenant-SLICED table reproduces the standalone
        lookup bit-exactly (hits AND matched slots)."""
        from jax.sharding import Mesh
        from jax.sharding import PartitionSpec as P

        from vpp_tpu.ops.session import session_lookup_reverse_idx
        from vpp_tpu.parallel.partition import (
            RULE_AXIS,
            ShardCtx,
            shard_map,
        )

        dp, up, pod = build_dp(tenants=[
            {"id": 1, "prefixes": [T1_NET], "sess_buckets": 8},
            {"id": 2, "prefixes": [T2_NET], "sess_buckets": 8},
        ], sess_slots=512)  # 128 buckets
        fwd = tenant_traffic(up, [(T1_NET, 10), (T2_NET, 6),
                                  ("172.16.0.0/16", 4)], n=32, seed=13)
        dp.process(fwd, now=1)
        reply = make_packet_vector([
            dict(src=".".join(str((int(np.asarray(fwd.dst_ip)[i]) >> s)
                                  & 255) for s in (24, 16, 8, 0)),
                 dst=".".join(str((int(np.asarray(fwd.src_ip)[i]) >> s)
                                  & 255) for s in (24, 16, 8, 0)),
                 proto=6, sport=80,
                 dport=int(np.asarray(fwd.sport)[i]), rx_if=pod)
            for i in range(20)
        ], n=32)
        t = dp.tables
        solo_hit, solo_idx = session_lookup_reverse_idx(
            t, reply, jnp.int32(2), tnt=True)
        solo_hit = np.asarray(solo_hit)
        assert solo_hit.sum() >= 16  # the differential has signal
        shards = 2
        mesh = Mesh(np.array(jax.devices("cpu")[:shards]), (RULE_AXIS,))
        ctx = ShardCtx(RULE_AXIS, shards)
        # the session bucket grids shard along the bucket axis; every
        # other field — the tenant planes included — replicates, the
        # PARTITION_RULES placement
        grid = {"sess_valid", "sess_src", "sess_dst", "sess_ports",
                "sess_proto", "sess_time"}
        nb = t.sess_valid.shape[0]
        assert nb % shards == 0
        tbl_specs = type(t)(**{
            f: (P(RULE_AXIS) if f in grid else P())
            for f in t._fields})

        def kernel(tbl, pv):
            return session_lookup_reverse_idx(
                tbl, pv, jnp.int32(2), shard=ctx, tnt=True)

        with mesh:
            sharded = shard_map(
                kernel, mesh=mesh,
                in_specs=(tbl_specs, P()),
                out_specs=(P(), P()),
            )
            mesh_hit, mesh_idx = sharded(t, reply)
        mesh_hit = np.asarray(mesh_hit)
        assert np.array_equal(mesh_hit, solo_hit)
        # matched slots agree wherever found: the mesh returns the
        # GLOBAL flat slot (shard-local recombined), identical to the
        # standalone index
        assert np.array_equal(np.asarray(mesh_idx)[solo_hit],
                              np.asarray(solo_idx)[solo_hit])


# --------------------------------------------------------------------
# host-side scheduling units
# --------------------------------------------------------------------


class TestShardRefusal:
    def test_mesh_refuses_tenancy_on(self):
        """The cluster step does not compile the tenant stage (yet):
        an enforcement feature must refuse loudly on the mesh, never
        silently skip quotas (the explicit-bv-refusal convention)."""
        from vpp_tpu.parallel.cluster import ClusterDataplane
        from vpp_tpu.parallel.mesh import cluster_mesh
        from vpp_tpu.parallel.multihost import MultiHostCluster

        cfg = DataplaneConfig(
            max_tables=2, max_rules=16, max_global_rules=32,
            max_ifaces=8, fib_slots=16, sess_slots=256, nat_mappings=2,
            nat_backends=2, tenancy="on", sess_sweep_stride=0)
        with pytest.raises(ValueError, match="tenancy"):
            ClusterDataplane(cluster_mesh(1, 1), cfg)
        with pytest.raises(ValueError, match="tenancy"):
            MultiHostCluster(1, cfg)


class TestScheduler:
    def test_wfq_proportional_service(self):
        s = TenantScheduler({1: 3, 2: 1})
        for i in range(12):
            s.push(1, 100 + i, 4)
            s.push(2, 200 + i, 4)
        served = {1: 0, 2: 0}
        for _ in range(16):
            t = s.pick()
            s.pop(t, 4)
            served[t] += 1
        # weight 3:1 -> tenant 1 gets ~3x the service
        assert served[1] == 12 and served[2] == 4

    def test_idle_rebase_prevents_banked_burst(self):
        s = TenantScheduler({1: 1, 2: 1})
        for i in range(8):
            s.push(1, i, 4)
        for _ in range(8):
            s.pop(s.pick(), 4)  # tenant 1 accrues vtime 32
        s.push(1, 100, 4)
        s.push(2, 200, 4)  # tenant 2 returns from idle
        # without the rebase tenant 2 would monopolize until its
        # vtime catches up from 0; WITH it, service alternates
        order = []
        for _ in range(2):
            t = s.pick()
            s.pop(t, 4)
            order.append(t)
        assert set(order) == {1, 2}

    def test_shed_pick_names_the_hog(self):
        s = TenantScheduler({1: 1, 2: 4})
        for i in range(4):
            s.push(1, i, 16)       # backlog 64 / weight 1 = 64
        for i in range(8):
            s.push(2, 100 + i, 16)  # backlog 128 / weight 4 = 32
        assert s.shed_pick() == 1  # most backlog PER UNIT WEIGHT
        s.pop(1, 1 << 30)
        assert s.shed_pick() == 2

    def test_requeue_front_restores_order_and_vtime(self):
        s = TenantScheduler({1: 1})
        for i in range(3):
            s.push(1, i, 4)
        frames = s.pop(1, 8)  # rids 0, 1
        assert [r for r, _ in frames] == [0, 1]
        v_after = s._vtime[1]
        assert v_after == 8.0
        s.requeue_front(1, frames)
        assert s._vtime[1] == 0.0
        assert [r for r, _ in s.pop(1, 1 << 30)] == [0, 1, 2]

    def test_pop_takes_at_least_one_frame(self):
        s = TenantScheduler()
        s.push(5, 0, 64)
        assert [r for r, _ in s.pop(5, 4)] == [0]  # oversize but first


class TestClassifier:
    def test_prefix_vni_and_frame(self):
        cls = TenantClassifier(tenant_entries_from_config([
            {"id": 1, "prefixes": [T1_NET], "weight": 3, "vni": 700},
            {"id": 2, "prefixes": [T2_NET]},
        ]))
        src = np.asarray([int(ip4("10.50.1.1")), int(ip4("1.1.1.1")),
                          int(ip4("1.1.1.1"))], np.uint32)
        dst = np.asarray([int(ip4("2.2.2.2")), int(ip4("10.60.0.9")),
                          int(ip4("3.3.3.3"))], np.uint32)
        assert cls.packet_tenants(src, dst).tolist() == [1, 2, 0]
        assert cls.tenant_of_vni(700) == 1
        assert cls.tenant_of_vni(999) == 0
        assert cls.weight(1) == 3 and cls.weight(2) == 1


# --------------------------------------------------------------------
# validation refusals
# --------------------------------------------------------------------


class TestValidation:
    def _cfg(self, **over):
        return DataplaneConfig(
            max_tables=2, max_rules=8, max_global_rules=8, max_ifaces=4,
            fib_slots=16, sess_slots=256, nat_mappings=2,
            nat_backends=2, tenancy="on", **over)

    @pytest.mark.parametrize("entries,frag", [
        ([{"id": 1}, {"id": 1}], "duplicate"),
        ([{"id": 99}], "outside"),
        ([{"id": 1, "prefixes": ["not-a-net"]}], ""),
        ([{"id": 1, "prefixes": ["fd00::/8"]}], "IPv4"),
        ([{"id": 1, "rate": (1 << 16) + 1}], "rate"),
        ([{"id": 1, "rate": 5}], "burst"),
        ([{"id": 1, "sess_buckets": 3}], "power of two"),
        ([{"id": 1, "sess_buckets": 128}], "exceeds"),
        ([{"id": 1, "sess_buckets": 32}, {"id": 2, "sess_buckets": 64}],
         "oversubscribed"),
        # cross-tenant overlapping prefixes: device first-match vs
        # host max would bill the same packet to different tenants
        ([{"id": 1, "prefixes": ["10.0.0.0/8"]},
          {"id": 2, "prefixes": ["10.60.0.0/16"]}], "overlap"),
        # slices fill the whole table while the implicit default
        # tenant 0 (unsliced) still needs residual bucket range
        ([{"id": 1, "sess_buckets": 64}], "residual"),
        ([{"id": 1, "weight": 0}], "weight"),
        ([{"id": 1, "ml_mode": "bogus"}], "ml_mode"),
        ([{"id": 1, "nonsense_key": 1}], "unknown"),
        ([{"name": "anonymous"}], "missing"),
    ])
    def test_refusals(self, entries, frag):
        with pytest.raises(ValueError) as ei:
            validate_tenancy_config(self._cfg(), entries)
        assert frag.lower() in str(ei.value).lower()

    def test_full_slicing_allowed_when_tenant0_sliced(self):
        """Slicing the WHOLE table is legal iff no unsliced tenant
        remains — i.e. the default tenant 0 registered its own
        slice."""
        entries = validate_tenancy_config(self._cfg(), [
            {"id": 0, "sess_buckets": 32},
            {"id": 1, "prefixes": [T1_NET], "sess_buckets": 32},
        ])
        assert len(entries) == 2

    def test_prefix_map_overflow_refused_at_load(self):
        """A prefix list larger than the device map fails AT CONFIG
        VALIDATION (load / set_tenant pre-staging), not as a
        _restage_tenants crash after the registry mutated."""
        with pytest.raises(ValueError, match="slots"):
            validate_tenancy_config(
                self._cfg(tenancy_prefixes=2),
                [{"id": 1, "prefixes": ["10.50.0.0/16", "10.51.0.0/16",
                                        "10.52.0.0/16"]}])

    def test_set_tenant_requires_knob(self):
        dp = Dataplane(DataplaneConfig(
            max_tables=2, max_rules=8, max_global_rules=8,
            max_ifaces=4, fib_slots=16, sess_slots=256, nat_mappings=2,
            nat_backends=2))
        with pytest.raises(ValueError, match="tenancy"):
            dp.builder.set_tenant(1, prefixes=[T1_NET])

    def test_agent_config_refuses_tenants_with_knob_off(self):
        from vpp_tpu.cmd.config import AgentConfig

        with pytest.raises(ValueError, match="tenancy"):
            AgentConfig.from_dict({
                "node_name": "n1",
                "tenants": [{"id": 1, "prefixes": [T1_NET]}],
            })
        # and loads cleanly with it on
        cfg = AgentConfig.from_dict({
            "node_name": "n1",
            "dataplane": {"tenancy": "on"},
            "tenants": [{"id": 1, "prefixes": [T1_NET], "weight": 2}],
        })
        assert cfg.tenants[0]["weight"] == 2

    def test_tenant_quantum_knob_validated_and_applied(self):
        from vpp_tpu.cmd.config import AgentConfig

        with pytest.raises(ValueError, match="io_tenant_quantum"):
            AgentConfig.from_dict({
                "node_name": "n1",
                "io": {"io_tenant_quantum": -1},
            })
        # the pump caps a WFQ take at the quantum (the isolation
        # bench's latency/throughput dial)
        dp, a, _b = _pump_dp()
        cls = TenantClassifier(tenant_entries_from_config(
            [{"id": 1, "prefixes": [T1_NET]}]))
        rings = IORingPair(n_slots=16)
        pump = DataplanePump(dp, rings, mode="dispatch",
                             tenants=cls, tenant_quantum=8)
        try:
            assert pump.tenant_quantum == 8
            with pump._held_lock:
                for rid in range(3):
                    pump._tnt_sched.push(1, rid, 4)
            # a take pops at most the quantum (2 x 4-pkt frames)
            with pump._held_lock:
                frames = pump._tnt_sched.pop(1, min(
                    pump.max_batch, pump.tenant_quantum))
            assert [r for r, _ in frames] == [0, 1]
        finally:
            rings.close()

    def test_oversubscription_refused_before_staging_mutates(self):
        dp, up, _pod = build_dp(tenants=[
            {"id": 1, "prefixes": [T1_NET], "sess_buckets": 32},
        ])
        before = dict(dp.builder.tnt)
        with pytest.raises(ValueError, match="oversubscribed"):
            dp.builder.set_tenant(2, prefixes=[T2_NET],
                                  sess_buckets=64)
        for k, v in dp.builder.tnt.items():
            assert np.array_equal(v, before[k]), k
        assert 2 not in dp.builder.tenants


# --------------------------------------------------------------------
# pump integration: WFQ no-starvation + device quota drops
# --------------------------------------------------------------------


def _pump_dp():
    dp = Dataplane(DataplaneConfig(sess_slots=256, sess_sweep_stride=0))
    a = dp.add_pod_interface(("default", "a"))
    b = dp.add_pod_interface(("default", "b"))
    dp.builder.add_route("10.50.0.0/16", b, Disposition.LOCAL)
    dp.builder.add_route("10.60.0.0/16", b, Disposition.LOCAL)
    dp.builder.add_route("10.1.1.0/24", a, Disposition.LOCAL)
    dp.swap()
    return dp, a, b


def _push_tenant_frames(rings, codec, scratch, rx_if, src, n_frames,
                        per, tag0, seq0, seqs):
    pushed = 0
    for k in range(n_frames):
        frames = [make_frame(src, "10.1.1.2", proto=17,
                             sport=tag0 + k * 64 + j, dport=3000)
                  for j in range(per)]
        cols, n = codec.parse(frames, rx_if, scratch)
        cols["meta"][:n] = seq0 + k
        assert rings.rx.push(cols, n, payload=scratch)
        seqs.append(seq0 + k)
        pushed += n
    return pushed


class TestPumpWfq:
    def test_heavy_tenant_cannot_starve_light_tenant(self):
        """A saturating tenant-1 backlog sits FIRST in ring order;
        tenant 2 (weight 4) is queued behind all 48 of its frames —
        both pushed BEFORE the pump starts, so the scenario carries no
        wall-clock race at all (a sleep-based "arrives later" phase
        stretches unboundedly on a loaded single-core box; arrival-
        order fairness in TIME is TestScheduler's idle-rebase unit).
        FIFO would serve the whole tenant-1 backlog before tenant 2;
        weighted-fair dequeue must interleave tenant 2 within a few
        quanta. (Egress stays ring-ordered by the tx writer's
        done-prefix, so the observable fairness signal is service
        ORDER: the pump's monotone per-tenant ``last_admit_seq``,
        read after the full drain — poll-free.) Conservation exact,
        lane accounting populated."""
        dp, a, _b = _pump_dp()
        cls = TenantClassifier(tenant_entries_from_config([
            {"id": 1, "prefixes": [T1_NET], "weight": 1},
            {"id": 2, "prefixes": [T2_NET], "weight": 4},
        ]))
        rings = IORingPair(n_slots=128)
        pump = DataplanePump(dp, rings, mode="dispatch",
                             max_batch=VEC, max_inflight=1,
                             fetch_delay=0.06, tenants=cls)
        codec = PacketCodec()
        scratch = np.zeros((VEC, rings.rx.snap), np.uint8)
        t1_seqs, t2_seqs = [], []
        offered = _push_tenant_frames(
            rings, codec, scratch, a, "10.50.1.1", 48, 16, 10000, 0,
            t1_seqs)
        offered += _push_tenant_frames(
            rings, codec, scratch, a, "10.60.1.1", 4, 4, 20000,
            100, t2_seqs)
        pump.start()
        try:
            drained = 0
            deadline = time.monotonic() + 180.0
            while drained < 52 and time.monotonic() < deadline:
                g = rings.tx.peek()
                if g is None:
                    time.sleep(0.005)
                    continue
                drained += 1
                rings.tx.release()
            assert drained == 52, "tx drain timed out"
            assert pump.stop(join_timeout=60.0)
            s = pump.stats
            assert s["pkts"] == offered
            tio = pump.tenant_io_snapshot()
            # service-order proof off the monotone admission sequence:
            # frames OTHER tenants were admitted before tenant 2
            # finished = tenant 2's last_admit_seq minus its own 4
            # frames. WFQ (weight 4 vs 1) serves tenant 2 within the
            # first few quanta even though all 48 tenant-1 frames sit
            # ahead of it in ring order; FIFO would put every one of
            # them first (seq 52). >10 frames still queued at tenant
            # 2's completion <=> at most 37 went before it.
            t1_before_t2_done = tio["io"][2]["last_admit_seq"] - 4
            assert t1_before_t2_done <= 37, (
                "light tenant waited out the heavy backlog (FIFO?): "
                f"{t1_before_t2_done} tenant-1 frames admitted before "
                "tenant 2 finished")
            assert tio["io"][1]["last_admit_seq"] \
                > tio["io"][2]["last_admit_seq"]
            assert tio["io"][1]["pkts"] == 48 * 16
            assert tio["io"][2]["pkts"] == 16
            assert tio["io"][1]["shed_pkts"] == 0  # no governor
            assert tio["weights"] == {1: 1, 2: 4}
        finally:
            pump.stop(join_timeout=30.0)
            rings.close()

    def test_priority_express_not_gated_by_tenant_scan_stall(self):
        """Tenants AND a PriorityFilter together: the scan frontier's
        tenant-lane stall (taken+done >= hold_cap) must NOT delay
        reflex classification — a priority frame behind a saturating
        bulk backlog takes service within a few WFQ quanta (the
        ISSUE 13 bounded-queueing contract), observable poll-free via
        the priority_admit_bulk_seq order signal."""
        from vpp_tpu.io.governor import PriorityFilter

        dp, a, _b = _pump_dp()
        cls = TenantClassifier(tenant_entries_from_config([
            {"id": 1, "prefixes": [T1_NET], "weight": 1},
        ]))
        rings = IORingPair(n_slots=16)  # hold_cap 12 < the backlog
        pump = DataplanePump(dp, rings, mode="dispatch",
                             max_batch=VEC, max_inflight=1,
                             fetch_delay=0.05, tenants=cls,
                             tenant_quantum=4,
                             priority=PriorityFilter(ports=(9999,)))
        codec = PacketCodec()
        scratch = np.zeros((VEC, rings.rx.snap), np.uint8)
        offered = 0
        seqs = []
        offered += _push_tenant_frames(
            rings, codec, scratch, a, "10.50.1.1", 14, 4, 10000, 0,
            seqs)
        # the reflex frame sits BEHIND the whole bulk backlog
        frames = [make_frame("10.50.9.9", "10.1.1.2", proto=17,
                             sport=5, dport=9999)]
        cols, n = codec.parse(frames, a, scratch)
        cols["meta"][:n] = 999
        assert rings.rx.push(cols, n, payload=scratch)
        offered += n
        pump.start()
        try:
            drained = 0
            deadline = time.monotonic() + 120.0
            while drained < 15 and time.monotonic() < deadline:
                g = rings.tx.peek()
                if g is None:
                    time.sleep(0.005)
                    continue
                drained += 1
                rings.tx.release()
            assert drained == 15, "tx drain timed out"
            assert pump.stop(join_timeout=30.0)
            s = pump.stats
            assert s["pkts"] == offered
            assert s["priority_frames"] == 1
            # the frontier never stalls on bulk occupancy with a
            # priority filter attached: the reflex frame classifies on
            # the FIRST scan pass and the express take outranks every
            # bulk lane, so it observes 0 bulk admissions (measured;
            # the reverted stall reads 5 — classification waits out
            # hold_cap releases)
            assert s["priority_admit_bulk_seq"] <= 2, \
                s["priority_admit_bulk_seq"]
        finally:
            pump.stop(join_timeout=30.0)
            rings.close()

    @pytest.mark.slow  # ~10 s: pump + WFQ bring-up; quota-drop conservation stays fast in TestQuotaDrops
    def test_device_quota_drops_surface_in_pump_stats(self):
        """Dispatch pump over a tenancy-on dataplane with a
        rate-limited tenant: the aux rider's DROP_TENANT count lands
        in stats['drops_tenant_quota'] and agrees EXACTLY with the
        device per-tenant plane."""
        dp, up, _pod = build_dp(tenants=[
            {"id": 1, "prefixes": [T1_NET], "rate": 1, "burst": 8},
        ])
        cls = TenantClassifier(tenant_entries_from_config([
            {"id": 1, "prefixes": [T1_NET]},
        ]))
        rings = IORingPair(n_slots=64)
        pump = DataplanePump(dp, rings, mode="dispatch",
                             max_batch=VEC, tenants=cls)
        codec = PacketCodec()
        scratch = np.zeros((VEC, rings.rx.snap), np.uint8)
        offered = 0
        for k in range(4):
            frames = [make_frame("10.50.2.3", "10.1.1.2", proto=17,
                                 sport=40000 + k * 64 + j, dport=53)
                      for j in range(16)]
            cols, n = codec.parse(frames, up, scratch)
            assert rings.rx.push(cols, n, payload=scratch)
            offered += n
        pump.start()
        try:
            deadline = time.monotonic() + 120.0
            while pump.stats["pkts"] < offered \
                    and time.monotonic() < deadline:
                while rings.tx.peek() is not None:
                    rings.tx.release()
                time.sleep(0.01)
            while rings.tx.peek() is not None:
                rings.tx.release()
            assert pump.stop(join_timeout=60.0)
            s = pump.stats
            assert s["pkts"] == offered
            assert s["drops_tenant_quota"] > 0  # 64 pkts vs burst 8
            snap = dp.tenant_snapshot()
            assert int(snap["rl_drops"][1]) == s["drops_tenant_quota"]
            assert int(snap["rx"][1]) == offered
            assert int(snap["tx"][1]) + int(snap["rl_drops"][1]) \
                == offered
        finally:
            pump.stop(join_timeout=30.0)
            rings.close()


# --------------------------------------------------------------------
# wiring: step-form contract, CLI, collector
# --------------------------------------------------------------------


class TestStepFormContract:
    @pytest.mark.jit_budget(4)
    def test_one_new_form_and_zero_io_callbacks(self):
        """The ISSUE 14 acceptance pair: tenancy adds exactly ONE
        step-form dimension value (the `_tenancy` label suffix on the
        same process-wide cache) and the persistent ring path stays
        io_callback-free with the stage compiled in."""
        from vpp_tpu.pipeline.dataplane import _JIT_STEPS, _step_label

        dp, up, _pod = build_dp(tenants=[
            {"id": 1, "prefixes": [T1_NET], "rate": 2, "burst": 4},
        ])
        before = set(_JIT_STEPS)
        dp.process(tenant_traffic(up, [(T1_NET, 4)], n=16, seed=20),
                   now=1)
        new = set(_JIT_STEPS) - before
        assert all(k[-1] == "on" for k in new), \
            f"non-tenancy variants appeared: {new}"
        assert "_tenancy" in _step_label(
            "dense", False, False, "plain", 0, tnt_mode="on")
        # ring path: the window program with tenancy on makes ZERO
        # host callbacks
        from vpp_tpu.pipeline.persistent import PersistentPump

        pp = PersistentPump(dp.tables, batch=VEC, fastpath=False,
                            tnt_mode="on").start()
        try:
            pv = tenant_traffic(up, [(T1_NET, 8)], n=VEC, seed=21)
            cols = {f: np.asarray(getattr(pv, f))
                    for f in ("src_ip", "dst_ip", "proto", "sport",
                              "dport", "ttl", "pkt_len", "rx_if",
                              "flags")}
            from vpp_tpu.pipeline.dataplane import (
                pack_packet_columns,
                packed_input_zeros,
            )

            flat = packed_input_zeros(VEC)
            pack_packet_columns(flat.view(np.uint32), cols, VEC)
            pp.submit(flat, now=2)
            out, aux = pp.result_ex(timeout=60.0)
            assert out is not None
            assert pp.stats_snapshot()["io_callbacks"] == 0
            # the tenancy aux rows rode the ring fetch
            from vpp_tpu.pipeline.dataplane import PACKED_AUX_SCHEMA

            rl_row = PACKED_AUX_SCHEMA.index("tnt_limited")
            assert np.asarray(aux)[rl_row] >= 0
        finally:
            pp.stop()

    def test_packed_aux_carries_tenancy_rows(self):
        from vpp_tpu.pipeline.dataplane import (
            PACKED_AUX_ROWS,
            PACKED_AUX_SCHEMA,
            pack_packet_columns,
            packed_input_zeros,
        )

        dp, up, _pod = build_dp(tenants=[
            {"id": 1, "prefixes": [T1_NET], "rate": 1, "burst": 2},
        ])
        pv = tenant_traffic(up, [(T1_NET, 8)], n=16, seed=22)
        flat = packed_input_zeros(16)
        cols = {f: np.asarray(getattr(pv, f))
                for f in ("src_ip", "dst_ip", "proto", "sport",
                          "dport", "ttl", "pkt_len", "rx_if", "flags")}
        pack_packet_columns(flat.view(np.uint32), cols, 16)
        _out, aux = dp.process_packed(flat, now=3, with_aux=True)
        aux_h = np.asarray(aux)
        assert aux_h.shape == (PACKED_AUX_ROWS,) \
            == (len(PACKED_AUX_SCHEMA),)
        assert aux_h[PACKED_AUX_SCHEMA.index("tnt_limited")] == 6
        assert aux_h[PACKED_AUX_SCHEMA.index("tnt_qfail")] == 0


class TestObservability:
    def test_show_tenants_and_collector_families(self):
        from vpp_tpu.cli import DebugCLI
        from vpp_tpu.stats.collector import StatsCollector

        dp, up, _pod = build_dp(tenants=[
            {"id": 1, "name": "gold", "prefixes": [T1_NET], "rate": 2,
             "burst": 4, "sess_buckets": 4, "weight": 3},
        ])
        res = dp.process(
            tenant_traffic(up, [(T1_NET, 8)], n=16, seed=23), now=100)
        cli = DebugCLI(dp)
        out = cli.run("show tenants")
        assert "tenant 1 (gold)" in out
        assert "rate 2/tick" in out
        assert "rl-drops 4" in out
        # the default tenant renders even with a non-empty registry:
        # unmatched traffic lands there and must stay observable
        assert "tenant 0" in out
        coll = StatsCollector(dp)
        coll.update(res.stats)  # the pump's per-frame ingestion path
        coll.publish()
        text = "\n".join(line for _p, fam in coll.registry.families()
                         for line in fam.render())
        assert 'vpp_tpu_tenant_goodput_packets{tenant="1"} 4' in text
        assert 'vpp_tpu_tenant_rl_dropped_packets{tenant="1"} 4' in text
        assert 'vpp_tpu_tenant_weight{tenant="1"} 3' in text
        assert 'vpp_tpu_tenant_rx_packets{tenant="0"}' in text
        assert "vpp_tpu_node_tenant_limited_packets 4" in text

    def test_trace_renders_tenant_quota_drop(self):
        """PacketTracer attributes DROP_TENANT to its own error-drop
        leaf right after ip4-input (the token bucket runs BEFORE
        session/ML/NAT/ACL) — never a fabricated forwarding path."""
        from vpp_tpu.trace.tracer import PacketTracer

        dp, up, _pod = build_dp(tenants=[
            {"id": 1, "prefixes": [T1_NET], "rate": 1, "burst": 2},
        ])
        tracer = PacketTracer()
        dp.tracer = tracer
        tracer.add(8)
        res = dp.process(
            tenant_traffic(up, [(T1_NET, 6)], n=8, seed=30), now=100)
        assert int(res.stats.tnt_limited) == 4  # burst 2 admits 2
        entries = tracer.entries()
        dropped = [e for e in entries
                   if e.drop_cause == "tenant-quota"]
        passed = [e for e in entries if e.drop_cause == "none"]
        assert len(dropped) == 4 and passed
        for e in dropped:
            assert e.path == ("ip4-input", "tenant-limit",
                              "error-drop (tenant-quota)")
        for e in passed:
            assert "error-drop (tenant-quota)" not in e.path

    def test_deleted_tenant_labelsets_removed(self):
        """A cleared tenant's per-tenant series must disappear from
        the next publish (the vpp_tpu_build_info stale-labelset
        discipline) — not export frozen ghost values forever."""
        from vpp_tpu.stats.collector import StatsCollector

        dp, up, _pod = build_dp(tenants=[
            {"id": 1, "prefixes": [T1_NET], "rate": 2, "burst": 4},
        ])
        dp.process(tenant_traffic(up, [(T1_NET, 8)], n=16, seed=24),
                   now=100)
        coll = StatsCollector(dp)
        coll.publish()

        def render():
            return "\n".join(line
                             for _p, fam in coll.registry.families()
                             for line in fam.render())

        assert 'vpp_tpu_tenant_rx_packets{tenant="1"}' in render()
        dp.builder.clear_tenants()
        dp.swap()
        coll.publish()
        text = render()
        assert 'vpp_tpu_tenant_rx_packets{tenant="1"}' not in text
        assert 'vpp_tpu_tenant_rx_packets{tenant="0"}' in text

    def test_show_tenants_off_dataplane(self):
        from vpp_tpu.cli import DebugCLI

        dp = Dataplane(DataplaneConfig(
            max_tables=2, max_rules=8, max_global_rules=8,
            max_ifaces=4, fib_slots=16, sess_slots=256, nat_mappings=2,
            nat_backends=2))
        assert "tenancy: off" in DebugCLI(dp).run("show tenants")
