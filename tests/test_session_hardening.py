"""Session-table hardening: counters, insert-time eviction, clock aging.

VERDICT r1 Weak #4/#5 + Next #7: probe-window congestion must be
observable (not a silent skip), expired entries must be reclaimed at
insert time (a full-but-stale window must not starve new flows), and
aging must follow the wall clock, not offered load.

Reference analog: VPP session/NAT timers + acl-plugin session counters
(SURVEY.md §5).
"""

from __future__ import annotations

import numpy as np


from vpp_tpu.ir.rule import Action, ContivRule, Protocol
from vpp_tpu.pipeline.dataplane import Dataplane
from vpp_tpu.pipeline.tables import DataplaneConfig
from vpp_tpu.pipeline.vector import Disposition, make_packet_vector
from vpp_tpu.stats.collector import StatsCollector


SMALL_SLOTS = 256


def make_dp(sess_slots=SMALL_SLOTS, max_age=50):
    dp = Dataplane(DataplaneConfig(
        sess_slots=sess_slots, sess_max_age=max_age,
        max_ifaces=8, fib_slots=16,
    ))
    client = dp.add_pod_interface(("d", "c"))
    server = dp.add_pod_interface(("d", "s"))
    dp.builder.add_route("10.1.1.2/32", client, Disposition.LOCAL)
    dp.builder.add_route("10.1.1.3/32", server, Disposition.LOCAL)
    dp.builder.set_global_table(
        [ContivRule(action=Action.PERMIT, protocol=Protocol.ANY)]
    )
    dp.swap()
    return dp, client, server


def flow_pkts(n, base_sport=1000, rx_if=1):
    return make_packet_vector([
        {"src": "10.1.1.2", "dst": "10.1.1.3", "proto": 6,
         "sport": base_sport + i, "dport": 80, "rx_if": rx_if}
        for i in range(n)
    ], n=max(256, n))


class TestCongestionCounters:
    def test_overload_is_fully_accounted(self):
        """Offer far more distinct flows than slots: under the
        set-associative table every offered flow must be visibly
        accounted — resident, failed (lost the intra-batch way
        election), or admitted-then-victim-evicted. Nothing silent:
        failed + resident + evicted == offered, exactly."""
        dp, client, _ = make_dp(sess_slots=SMALL_SLOTS)
        total_fail = total_vic = total_exp = 0
        offered = 0
        for batch in range(8):
            pkts = flow_pkts(256, base_sport=batch * 256, rx_if=client)
            res = dp.process(pkts, now=1)
            total_fail += int(res.stats.sess_insert_fail)
            total_vic += int(res.stats.sess_evict_victim)
            total_exp += int(res.stats.sess_evict_expired)
            offered += 256
        occ = int(res.stats.sess_occupancy)
        assert occ <= SMALL_SLOTS
        # a full live table admits new flows by evicting its oldest
        # (Gryphon-style churn), and the churn is COUNTED by reason
        assert total_vic > 0
        assert total_exp == 0  # nothing idled past max_age at now=1
        # heavy same-bucket pressure also loses some intra-batch way
        # elections — counted, retried on the flow's next packet
        assert total_fail > 0
        # conservation: every offered flow is exactly one of
        # resident / failed / evicted (all flows distinct, no refresh)
        assert total_fail + occ + total_vic == offered

    def test_occupancy_gauge_tracks_live_entries(self):
        dp, client, _ = make_dp()
        res = dp.process(flow_pkts(64, rx_if=client), now=1)
        assert int(res.stats.sess_occupancy) == 64
        assert int(res.stats.sess_insert_fail) == 0

    def test_counters_flow_to_prometheus(self):
        dp, client, _ = make_dp(sess_slots=SMALL_SLOTS)
        collector = StatsCollector(dp)
        for batch in range(8):
            res = dp.process(
                flow_pkts(256, base_sport=batch * 256, rx_if=client), now=1
            )
            collector.update(res.stats)
        collector.publish()
        text = collector.registry.render("/stats")
        assert "vpp_tpu_node_sess_insert_fail" in text
        fail_line = [l for l in text.splitlines()
                     if l.startswith("vpp_tpu_node_sess_insert_fail")][0]
        assert float(fail_line.split()[-1]) > 0
        occ_line = [l for l in text.splitlines()
                    if l.startswith("vpp_tpu_node_sess_occupancy")][0]
        assert 0 < float(occ_line.split()[-1]) <= SMALL_SLOTS


class TestInsertTimeEviction:
    def test_stale_window_does_not_starve_new_flows(self):
        """Fill the table, let everything idle past max_age, then insert
        fresh flows WITHOUT running the host aging loop: inserts must
        reclaim expired slots in place."""
        dp, client, _ = make_dp(sess_slots=SMALL_SLOTS, max_age=50)
        res = dp.process(flow_pkts(256, rx_if=client), now=1)
        assert int(res.stats.sess_occupancy) > 200

        # far past max_age, no expire_sessions() call in between: offer
        # 128 fresh flows (50% load). Without eviction nearly all would
        # fail (stale entries still hold >200 slots); with insert-time
        # eviction a miss needs MORE than sess_ways new flows hashing
        # into one bucket in one batch — a bounded tail, not
        # starvation, and the reclaims are counted {reason=expired}.
        res2 = dp.process(
            flow_pkts(128, base_sport=5000, rx_if=client), now=1000
        )
        fails = int(res2.stats.sess_insert_fail)
        assert fails <= 128 * 0.15, f"miss rate not bounded: {fails}/128"
        # most inserts reclaimed a stale way (some land on never-used
        # ways of underfilled buckets — those are not evictions)
        assert int(res2.stats.sess_evict_expired) > 0
        assert int(res2.stats.sess_evict_victim) == 0
        # occupancy counts only live entries: stale ones are invisible,
        # the fresh flows (minus bounded misses) are present
        occ = int(res2.stats.sess_occupancy)
        assert 128 - fails <= occ <= 128

    def test_expired_session_no_longer_admits_replies(self):
        """A reply that relied on a reflective session must be denied
        once the session is idle past max_age — purely via the in-kernel
        check, no host aging."""
        dp, client, server = make_dp(max_age=50)
        # restrict policy: server->client traffic has no permit of its own
        slot = dp.alloc_table_slot("t")
        import ipaddress

        dp.builder.set_local_table(slot, [
            ContivRule(action=Action.PERMIT,
                       dest_network=ipaddress.ip_network("10.1.1.3/32"),
                       protocol=Protocol.TCP, dest_port=80),
            ContivRule(action=Action.DENY, protocol=Protocol.ANY),
        ])
        dp.assign_pod_table(("d", "c"), "t")
        dp.builder.set_local_table(  # same table on server rx
            dp.alloc_table_slot("t2"),
            [ContivRule(action=Action.DENY, protocol=Protocol.ANY)],
        )
        dp.assign_pod_table(("d", "s"), "t2")
        dp.swap()

        fwd = make_packet_vector([
            {"src": "10.1.1.2", "dst": "10.1.1.3", "proto": 6,
             "sport": 2000, "dport": 80, "rx_if": client}
        ])
        rep = make_packet_vector([
            {"src": "10.1.1.3", "dst": "10.1.1.2", "proto": 6,
             "sport": 80, "dport": 2000, "rx_if": server}
        ])
        assert int(dp.process(fwd, now=1).disp[0]) == int(Disposition.LOCAL)
        # within max_age: reply admitted via the reflective session
        r1 = dp.process(rep, now=40)
        assert bool(r1.established[0])
        assert int(r1.disp[0]) == int(Disposition.LOCAL)
        # replies kept the session alive (timestamps refreshed at 40):
        # still admitted at 40+45 < 40+max_age
        r2 = dp.process(rep, now=85)
        assert bool(r2.established[0])
        # idle past max_age since the last hit: denied
        r3 = dp.process(rep, now=85 + 51)
        assert not bool(r3.established[0])
        assert int(r3.disp[0]) == int(Disposition.DROP)

    def test_active_flow_never_expires(self):
        """Traffic every max_age/2 keeps the session alive indefinitely
        (hits refresh timestamps)."""
        dp, client, server = make_dp(max_age=50)
        fwd = make_packet_vector([
            {"src": "10.1.1.2", "dst": "10.1.1.3", "proto": 6,
             "sport": 2001, "dport": 80, "rx_if": client}
        ])
        rep = make_packet_vector([
            {"src": "10.1.1.3", "dst": "10.1.1.2", "proto": 6,
             "sport": 80, "dport": 2001, "rx_if": server}
        ])
        dp.process(fwd, now=1)
        for t in range(25, 500, 25):
            r = dp.process(rep, now=t)
            assert bool(r.established[0]), f"expired at t={t}"


class TestWallClockAging:
    def test_process_now_uses_clock_ticks(self):
        dp, client, _ = make_dp()
        dp.process(flow_pkts(1, rx_if=client))
        t1 = dp._now
        dp.advance_clock(12.0)  # simulate 12 idle seconds
        dp.process(flow_pkts(1, rx_if=client))
        assert dp._now - t1 >= 12 * Dataplane.TICKS_PER_SEC

    def test_expiry_follows_wall_clock_not_load(self):
        """Many frames in zero wall time must NOT age sessions (the r1
        bug: aging counted frames); idle wall time must."""
        dp, client, server = make_dp(max_age=50)  # 5 seconds
        fwd = make_packet_vector([
            {"src": "10.1.1.2", "dst": "10.1.1.3", "proto": 6,
             "sport": 2002, "dport": 80, "rx_if": client}
        ])
        rep = make_packet_vector([
            {"src": "10.1.1.3", "dst": "10.1.1.2", "proto": 6,
             "sport": 80, "dport": 2002, "rx_if": server}
        ])
        dp.process(fwd)
        # heavy load, no elapsed time: hundreds of frames
        for _ in range(50):
            dp.process(flow_pkts(64, base_sport=7000, rx_if=client))
        assert bool(dp.process(rep).established[0])
        # now idle past the timeout in wall-clock terms
        dp.advance_clock(6.0)
        assert not bool(dp.process(rep).established[0])

    def test_expire_sessions_reclaims_slots(self):
        dp, client, _ = make_dp(max_age=50)
        dp.process(flow_pkts(64, rx_if=client))
        dp.advance_clock(10.0)
        expired = dp.expire_sessions()
        assert expired >= 64
        assert int(np.asarray(dp.tables.sess_valid).sum()) == 0


class TestElectionStrategies:
    """The claim (scatter-min) and sort (stable-argsort) slot elections
    must be bit-identical across every collision/eviction/conflict
    shape — the backend-dependent auto-selection (ops/session.py module
    doc) is only sound if the strategies can never disagree."""

    def test_claim_and_sort_elections_identical(self, monkeypatch):
        import jax
        import jax.numpy as jnp

        from vpp_tpu.ops import session as sess

        rng = np.random.default_rng(11)
        for trial in range(10):
            slots = int(rng.choice([64, 256, 1024]))
            n = int(rng.choice([64, 256]))
            results = {}
            for mode in ("claim", "sort"):
                monkeypatch.setenv("VPPT_SESS_ELECTION", mode)
                dp = Dataplane(DataplaneConfig(
                    max_tables=2, max_rules=8, max_global_rules=8,
                    max_ifaces=4, fib_slots=16, sess_slots=slots,
                    nat_mappings=2, nat_backends=2))
                dp.add_uplink()
                dp.swap()
                fn = jax.jit(sess.session_insert)
                t = dp.tables
                masks = []
                r2 = np.random.default_rng(trial)  # same traffic per mode
                for step in range(4):
                    pv = make_packet_vector(
                        [{"src": "10.0.0.1", "dst": "10.1.1.3",
                          "proto": 6, "sport": 1024, "dport": 80,
                          "rx_if": 1}], n=n)
                    n_flows = int(r2.choice([4, 16, n]))
                    fsrc = r2.integers(1, 1 << 24, n_flows).astype(np.uint32)
                    fsport = r2.integers(1024, 60000, n_flows).astype(np.int32)
                    pick = r2.integers(0, n_flows, n)
                    pv = pv._replace(
                        src_ip=jnp.asarray(fsrc[pick]),
                        sport=jnp.asarray(fsport[pick]),
                        flags=jnp.asarray(
                            r2.integers(0, 2, n).astype(np.int32)))
                    want = jnp.asarray(
                        r2.integers(0, 2, n).astype(bool)) & pv.valid
                    t, ins, fail, ev_e, ev_v = fn(
                        t, pv, want, jnp.int32(step + 1))
                    masks.append((np.asarray(ins), np.asarray(fail),
                                  np.asarray(ev_e), np.asarray(ev_v)))
                results[mode] = (t, masks)
            tc, mc = results["claim"]
            ts, ms = results["sort"]
            for claim_masks, sort_masks in zip(mc, ms):
                for a, b in zip(claim_masks, sort_masks):
                    assert np.array_equal(a, b), trial
            for f in ("sess_valid", "sess_src", "sess_dst",
                      "sess_ports", "sess_proto", "sess_time"):
                assert np.array_equal(np.asarray(getattr(tc, f)),
                                      np.asarray(getattr(ts, f))), (trial, f)
