"""Per-packet ML scoring stage (ISSUE 10): differential suite.

The device kernel (ops/mlscore.py) is validated against an INDEPENDENT
NumPy fixed-point oracle implemented in THIS file from the documented
contract (docs/ML_STAGE.md) — not against vpp_tpu.ml.model's own
reference — so a shared bug can't vouch for itself. Equality is
bit-exactness everywhere: the whole pipeline is exact integer math.

Covers: float-train → int8-pack → device-inference round trips,
degenerate models (all-zero weights, single feature, threshold
extremes), score/enforce pipeline differentials over mixed traffic
(flags/lengths/session states), verdict ordering (deny beats ml-drop
beats permit), the rate-limit flow gate, fastpath interplay (the fast
tier still scores, bit-exactly), epoch-swap plane reuse (ACL churn
re-ships NOTHING of the model), artifact load refusals, and the
packed-path aux riders.
"""

import json

import numpy as np
import pytest

import jax.numpy as jnp

from vpp_tpu.ml.model import MlModel, MlModelError, load_model, save_model
from vpp_tpu.ml.train import make_synth_dataset, quantize_mlp, train_mlp
from vpp_tpu.ops.mlscore import ML_FEATURES, ml_score
from vpp_tpu.pipeline.dataplane import Dataplane
from vpp_tpu.pipeline.graph import DROP_ACL, DROP_ML
from vpp_tpu.pipeline.tables import DataplaneConfig
from vpp_tpu.pipeline.vector import Disposition, make_packet_vector

POD_NET = "10.1.1.0/24"


# --------------------------------------------------------------------
# the independent oracle (docs/ML_STAGE.md contract, from scratch)
# --------------------------------------------------------------------


def oracle_features(pv, established, age):
    """uint8 [P, 18] features straight from the documented layout."""
    src = np.asarray(pv.src_ip, dtype=np.uint32)
    dst = np.asarray(pv.dst_ip, dtype=np.uint32)
    n = len(src)
    f = np.zeros((n, ML_FEATURES), np.int64)
    for j, sh in enumerate((24, 16, 8, 0)):
        f[:, j] = (src >> sh) & 0xFF
        f[:, 4 + j] = (dst >> sh) & 0xFF
    sport = np.asarray(pv.sport, np.int64)
    dport = np.asarray(pv.dport, np.int64)
    f[:, 8], f[:, 9] = (sport >> 8) & 0xFF, sport & 0xFF
    f[:, 10], f[:, 11] = (dport >> 8) & 0xFF, dport & 0xFF
    f[:, 12] = np.asarray(pv.proto, np.int64) & 0xFF
    f[:, 13] = np.minimum(np.asarray(pv.pkt_len, np.int64) >> 4, 255)
    f[:, 14] = np.asarray(pv.flags, np.int64) & 0xFF
    f[:, 15] = np.where(np.asarray(established, bool), 255, 0)
    f[:, 16] = np.clip(np.asarray(age, np.int64), 0, 255)
    return f


def oracle_scores(model: MlModel, feats: np.ndarray) -> np.ndarray:
    """Exact int64 inference from the UNFOLDED artifact fields (the
    device computes the zero-point-folded form; integer math makes
    them equal, which is exactly what this oracle checks)."""
    x = feats[:, : model.n_features].astype(np.int64)
    if model.kind == "mlp":
        a1 = x @ model.w1.astype(np.int64) + model.b1.astype(np.int64)
        q1 = np.clip(np.maximum(a1, 0) >> int(model.s1), 0, 255)
        return q1 @ model.w2.astype(np.int64) + int(model.b2)
    t, d = model.f_feat.shape
    bits = x[:, model.f_feat.reshape(-1)] > \
        model.f_thresh.reshape(-1)[None, :]
    leaf = (bits.reshape(-1, t, d).astype(np.int64)
            << np.arange(d, dtype=np.int64)[None, None, :]).sum(axis=2)
    return model.f_leaf.astype(np.int64)[
        np.arange(t)[None, :], leaf].sum(axis=1) + int(model.b2)


def oracle_flow_hash(pv) -> np.ndarray:
    """The rate-limit admission hash, re-derived (uint32 wraparound)."""
    M = np.uint64(0xFFFFFFFF)

    def mul(a, k):
        return (a.astype(np.uint64) * np.uint64(k)) & M

    src = np.asarray(pv.src_ip, np.uint32)
    dst = np.asarray(pv.dst_ip, np.uint32)
    ports = ((np.asarray(pv.sport, np.uint64) << np.uint64(16))
             | np.asarray(pv.dport, np.uint64)) & M
    proto = np.asarray(pv.proto, np.uint32)
    h = mul(src, 0x9E3779B1)
    h ^= mul(dst, 0x85EBCA77)
    h ^= (ports * np.uint64(0xC2B2AE3D)) & M
    h ^= mul(proto, 0x27D4EB2F)
    h ^= h >> np.uint64(15)
    return h.astype(np.uint32)


def device_scores(tables, pv, established, age) -> np.ndarray:
    kind = "forest" if int(tables.glb_ml_f_leaf.shape[1]) > 2 and \
        bool(np.any(np.asarray(tables.glb_ml_f_leaf))) else "mlp"
    return np.asarray(ml_score(
        tables, pv, jnp.asarray(np.asarray(established, bool)),
        jnp.asarray(np.asarray(age, np.int32)), kind=kind))


def proto_model(flag_thresh: int = 10, action: str = "drop",
                rl_shift: int = 0, version: int = 1) -> MlModel:
    """Hand-crafted deterministic model: score == the packet's proto
    byte (w1 picks feature 12 through a unit hidden path). flag_thresh
    10 flags UDP (17) and not TCP (6) — a fully predictable policy
    for the verdict-ordering tests."""
    w1 = np.zeros((ML_FEATURES, 4), np.int8)
    w1[12, 0] = 1
    return MlModel(
        kind="mlp", version=version, n_features=ML_FEATURES,
        w1=w1, b1=np.zeros(4, np.int32), s1=0,
        w2=np.array([1, 0, 0, 0], np.int8), b2=0,
        flag_thresh=flag_thresh, action=action, rl_shift=rl_shift,
    ).validate()


def build_dp(ml_stage="enforce", model=None, rules=(), fastpath=True,
             ml_hidden=16, ml_trees=4, ml_depth=3):
    cfg = DataplaneConfig(
        max_tables=2, max_rules=8, max_global_rules=32, max_ifaces=8,
        fib_slots=16, sess_slots=256, nat_mappings=2, nat_backends=4,
        ml_stage=ml_stage, ml_hidden=ml_hidden, ml_trees=ml_trees,
        ml_depth=ml_depth, fastpath=fastpath)
    dp = Dataplane(cfg)
    uplink = dp.add_uplink()
    pod_if = dp.add_pod_interface(("default", "pod"))
    dp.builder.add_route(POD_NET, pod_if, Disposition.LOCAL)
    dp.builder.add_route("0.0.0.0/0", uplink, Disposition.REMOTE,
                         node_id=1)
    if rules:
        dp.builder.set_global_table(list(rules))
    if model is not None:
        dp.builder.set_ml_model(model)
    dp.swap()
    return dp, uplink


def rand_traffic(n, uplink, seed=0, n_pkts=None):
    """Seeded mixed traffic: varied addresses/ports/lengths/flags and
    protos, some invalid slots."""
    rng = np.random.default_rng(seed)
    pkts = []
    for i in range(n):
        pkts.append(dict(
            src=f"172.{16 + i % 4}.{rng.integers(0, 256)}."
                f"{rng.integers(1, 255)}",
            dst=f"10.1.1.{rng.integers(2, 250)}",
            proto=int(rng.choice([6, 17, 1])),
            sport=int(rng.integers(1, 65535)),
            dport=int(rng.integers(1, 65535)),
            len=int(rng.integers(40, 4500)),
            rx_if=uplink,
        ))
    return make_packet_vector(pkts, n=n_pkts or n)


# --------------------------------------------------------------------
# quantization round trips + degenerate models (kernel level)
# --------------------------------------------------------------------


class TestQuantizationRoundTrip:
    def test_trained_mlp_device_matches_oracle_bit_exact(self, tmp_path):
        feats, labels = make_synth_dataset(1024, seed=3)
        w1, b1, w2, b2 = train_mlp(feats, labels, hidden=8, epochs=60)
        model = quantize_mlp(w1, b1, w2, b2, feats)
        path = tmp_path / "m.json"
        save_model(model, str(path))
        loaded = load_model(str(path))
        # artifact round trip is lossless
        np.testing.assert_array_equal(model.w1, loaded.w1)
        np.testing.assert_array_equal(model.b1, loaded.b1)
        assert (model.s1, model.b2, model.flag_thresh) == \
            (loaded.s1, loaded.b2, loaded.flag_thresh)
        dp, uplink = build_dp("score", loaded, ml_hidden=8)
        for seed in (1, 2, 3):
            pv = rand_traffic(64, uplink, seed=seed)
            est = np.zeros(64, bool)
            est[::3] = True
            age = np.where(est, (seed * 37) % 300, 0)
            dev = device_scores(dp.tables, pv, est, age)
            ora = oracle_scores(loaded, oracle_features(pv, est, age))
            np.testing.assert_array_equal(dev, ora.astype(np.int64))

    def test_all_zero_weights_scores_zero(self):
        model = MlModel(
            kind="mlp", version=1, n_features=ML_FEATURES,
            w1=np.zeros((ML_FEATURES, 2), np.int8),
            b1=np.zeros(2, np.int32), s1=0,
            w2=np.zeros(2, np.int8), b2=0, flag_thresh=0,
        ).validate()
        dp, uplink = build_dp("score", model, ml_hidden=2)
        pv = rand_traffic(32, uplink, seed=9)
        dev = device_scores(dp.tables, pv, np.zeros(32, bool),
                            np.zeros(32))
        assert (dev == 0).all()
        # score 0 is NOT > flag_thresh 0: nothing flags
        res = dp.process(pv, now=1)
        assert int(res.stats.ml_flagged) == 0

    def test_single_feature_model(self):
        """A 1-feature (packet length bucket), 1-hidden model — the
        smallest expressible artifact — pads up to capacity and stays
        bit-exact."""
        model = MlModel(
            kind="mlp", version=1, n_features=1,
            w1=np.array([[2]], np.int8), b1=np.array([-10], np.int32),
            s1=1, w2=np.array([3], np.int8), b2=7, flag_thresh=50,
        ).validate()
        dp, uplink = build_dp("score", model)
        pv = rand_traffic(48, uplink, seed=4)
        dev = device_scores(dp.tables, pv, np.zeros(48, bool),
                            np.zeros(48))
        # n_features=1 => only the src_ip MSB feature feeds the model
        feats = oracle_features(pv, np.zeros(48, bool), np.zeros(48))
        ora = oracle_scores(model, feats)
        np.testing.assert_array_equal(dev, ora)

    def test_threshold_extremes(self):
        """Flag threshold at the score-space extremes: everything
        below INT32_MIN-ish flags, nothing at INT32_MAX; forest
        feature thresholds at 0 and 255 pin the bit boundaries."""
        lo = proto_model(flag_thresh=-(1 << 30), action="mark")
        hi = proto_model(flag_thresh=(1 << 30), action="mark")
        dp, uplink = build_dp("score", lo)
        pv = rand_traffic(32, uplink, seed=5)
        res = dp.process(pv, now=1)
        assert int(res.stats.ml_flagged) == int(res.stats.ml_scored) > 0
        with dp.commit_lock:
            dp.builder.set_ml_model(hi)
            dp.swap()
        res = dp.process(rand_traffic(32, uplink, seed=6), now=2)
        assert int(res.stats.ml_flagged) == 0
        # forest: feature threshold 255 => bit never set (values are
        # uint8); threshold 0 => bit set iff value > 0
        forest = MlModel(
            kind="forest", version=1, n_features=ML_FEATURES,
            f_feat=np.array([[12, 12]], np.int32),
            f_thresh=np.array([[255, 0]], np.int32),
            f_leaf=np.array([[0, 11, 22, 33]], np.int32),
            flag_thresh=15,
        ).validate()
        dpf, upf = build_dp("score", forest, ml_trees=1, ml_depth=2)
        pvf = rand_traffic(32, upf, seed=7)
        dev = device_scores(dpf.tables, pvf, np.zeros(32, bool),
                            np.zeros(32))
        # proto > 255 never true -> bit0 off; proto > 0 always true ->
        # bit1 on -> leaf 2 (value 22) for every packet
        assert (dev == 22).all()
        ora = oracle_scores(
            forest, oracle_features(pvf, np.zeros(32, bool),
                                    np.zeros(32)))
        np.testing.assert_array_equal(dev, ora)

    def test_forest_device_matches_oracle(self):
        rng = np.random.default_rng(11)
        forest = MlModel(
            kind="forest", version=3, n_features=ML_FEATURES,
            f_feat=rng.integers(0, ML_FEATURES, (4, 3)).astype(np.int32),
            f_thresh=rng.integers(0, 256, (4, 3)).astype(np.int32),
            f_leaf=rng.integers(-500, 500, (4, 8)).astype(np.int32),
            b2=-17, flag_thresh=0,
        ).validate()
        dp, uplink = build_dp("score", forest)
        for seed in (1, 8):
            pv = rand_traffic(64, uplink, seed=seed)
            est = np.zeros(64, bool)
            est[1::4] = True
            age = np.where(est, 123, 0)
            dev = device_scores(dp.tables, pv, est, age)
            ora = oracle_scores(forest,
                                oracle_features(pv, est, age))
            np.testing.assert_array_equal(dev, ora)


# --------------------------------------------------------------------
# pipeline differential: score / enforce over mixed session states
# --------------------------------------------------------------------


def _deny_rule(src_cidr: str):
    import ipaddress

    from vpp_tpu.ir.rule import Action, ContivRule, Protocol

    return ContivRule(action=Action.DENY, protocol=Protocol.TCP,
                      src_network=ipaddress.ip_network(src_cidr))


def _permit_all():
    from vpp_tpu.ir.rule import Action, ContivRule, Protocol

    return ContivRule(action=Action.PERMIT, protocol=Protocol.ANY)


class TestPipelineDifferential:
    def _mixed_scenario(self, ml_stage: str, action: str = "drop",
                        rl_shift: int = 0):
        """Prime reflective sessions from pod-side traffic, then score
        a reply batch that mixes established/new flows, TCP/UDP/ICMP,
        and varied lengths — with the apply-global table permitting
        everything (the ML verdict is the only drop source)."""
        model = proto_model(action=action, rl_shift=rl_shift)
        dp, uplink = build_dp(ml_stage, model, rules=[_permit_all()])
        # forward (pod -> world) traffic installs reflective sessions
        fwd = make_packet_vector([
            dict(src=f"10.1.1.{2 + i}", dst=f"172.16.0.{10 + i}",
                 proto=6, sport=5000 + i, dport=80, rx_if=1)
            for i in range(8)
        ], n=32)
        r0 = dp.process(fwd, now=100)
        assert int(r0.stats.tx) == 8
        # replies: 8 established TCP + 8 fresh UDP + 8 fresh TCP
        reply = make_packet_vector(
            [dict(src=f"172.16.0.{10 + i}", dst=f"10.1.1.{2 + i}",
                  proto=6, sport=80, dport=5000 + i, len=600,
                  rx_if=uplink) for i in range(8)]
            + [dict(src=f"198.18.0.{i}", dst=f"10.1.1.{2 + i}",
                    proto=17, sport=53, dport=9000 + i, len=60,
                    rx_if=uplink) for i in range(8)]
            + [dict(src=f"198.19.0.{i}", dst=f"10.1.1.{2 + i}",
                    proto=6, sport=443, dport=9100 + i, len=1500,
                    rx_if=uplink) for i in range(8)],
            n=32)
        established = np.zeros(32, bool)
        established[:8] = True
        age = np.where(established, 7, 0)  # scored at now=107
        res = dp.process(reply, now=107)
        return dp, model, reply, established, age, res

    def test_score_mode_counts_but_never_drops(self):
        dp, model, pv, est, age, res = self._mixed_scenario("score")
        feats = oracle_features(pv, est, age)
        want_flag = oracle_scores(model, feats) > model.flag_thresh
        want_flag &= np.asarray(pv.valid)
        np.testing.assert_array_equal(
            np.asarray(res.ml_flagged), want_flag)
        assert int(res.stats.ml_scored) == 24
        assert int(res.stats.ml_flagged) == int(want_flag.sum()) == 8
        assert int(res.stats.ml_drops) == 0
        # nothing dropped: all 24 valid packets forwarded
        assert int(res.stats.tx) == 24
        assert not (np.asarray(res.drop_cause) == DROP_ML).any()

    def test_enforce_mode_drops_flagged_bit_exact(self):
        dp, model, pv, est, age, res = self._mixed_scenario("enforce")
        feats = oracle_features(pv, est, age)
        want_drop = oracle_scores(model, feats) > model.flag_thresh
        want_drop &= np.asarray(pv.valid)
        got_ml = np.asarray(res.drop_cause) == DROP_ML
        np.testing.assert_array_equal(got_ml, want_drop)
        assert int(res.stats.ml_drops) == int(want_drop.sum()) == 8
        assert int(res.stats.tx) == 24 - 8
        # dropped packets have no egress
        assert (np.asarray(res.tx_if)[want_drop] == -1).all()
        assert (np.asarray(res.disp)[want_drop]
                == int(Disposition.DROP)).all()

    def test_enforce_established_flows_also_policed(self):
        """An established (session-hit) flow whose score crosses the
        threshold still drops — DDoS rides established flows too."""
        # prime the session with a never-flagging model, THEN swap in
        # the aggressive one (an enforce drop would otherwise have
        # blocked the session install — by design)
        dp, uplink = build_dp(
            "enforce", proto_model(flag_thresh=(1 << 30)),
            rules=[_permit_all()])
        fwd = make_packet_vector([
            dict(src="10.1.1.2", dst="172.16.0.9", proto=6,
                 sport=5000, dport=80, rx_if=1)], n=8)
        dp.process(fwd, now=10)
        with dp.commit_lock:
            dp.builder.set_ml_model(
                proto_model(flag_thresh=1, action="drop"))  # flags all
            dp.swap()
        reply = make_packet_vector([
            dict(src="172.16.0.9", dst="10.1.1.2", proto=6, sport=80,
                 dport=5000, rx_if=uplink)], n=8)
        res = dp.process(reply, now=11)
        assert int(res.stats.sess_hits) == 1
        assert int(res.stats.ml_drops) == 1
        assert int(np.asarray(res.drop_cause)[0]) == DROP_ML

    def test_ratelimit_admits_by_flow_hash(self):
        dp, model, pv, est, age, res = self._mixed_scenario(
            "enforce", action="ratelimit", rl_shift=1)
        feats = oracle_features(pv, est, age)
        flagged = oracle_scores(model, feats) > model.flag_thresh
        flagged &= np.asarray(pv.valid)
        admit = (oracle_flow_hash(pv) & np.uint32(1)) == 0
        want_drop = flagged & ~admit
        got_ml = np.asarray(res.drop_cause) == DROP_ML
        np.testing.assert_array_equal(got_ml, want_drop)
        assert int(res.stats.ml_flagged) == int(flagged.sum())
        assert int(res.stats.ml_drops) == int(want_drop.sum())
        # the gate is per FLOW and deterministic: a second identical
        # batch drops exactly the same packets
        res2 = dp.process(pv, now=108)
        np.testing.assert_array_equal(
            np.asarray(res2.drop_cause) == DROP_ML, want_drop)

    def test_mirror_action_marks_without_dropping(self):
        dp, model, pv, est, age, res = self._mixed_scenario(
            "enforce", action="mirror")
        assert int(res.stats.ml_flagged) == 8
        assert int(res.stats.ml_drops) == 0
        assert int(res.stats.tx) == 24
        # the mirror mask is the flagged mask, exposed per packet
        assert int(np.asarray(res.ml_flagged).sum()) == 8


class TestVerdictOrdering:
    def test_deny_beats_ml_drop_beats_permit(self):
        """The pinned ordering: an ACL-denied packet attributes
        DROP_ACL even when the model also flags it; an ACL-permitted
        flagged packet attributes DROP_ML; unflagged permitted
        traffic forwards."""
        # model flags EVERY packet (threshold below any score)
        model = proto_model(flag_thresh=-1, action="drop")
        dp, uplink = build_dp(
            "enforce", model,
            rules=[_deny_rule("198.51.100.0/24"), _permit_all()])
        pv = make_packet_vector([
            # ACL-denied AND ml-flagged -> DROP_ACL wins
            dict(src="198.51.100.7", dst="10.1.1.2", proto=6,
                 sport=1234, dport=80, rx_if=uplink),
            # permitted AND ml-flagged -> DROP_ML
            dict(src="172.16.0.9", dst="10.1.1.3", proto=6,
                 sport=1234, dport=80, rx_if=uplink),
        ], n=8)
        res = dp.process(pv, now=1)
        cause = np.asarray(res.drop_cause)
        assert int(cause[0]) == DROP_ACL
        assert int(cause[1]) == DROP_ML
        assert int(res.stats.drop_acl) == 1
        assert int(res.stats.ml_drops) == 1
        # flip to a never-flagging model: the permitted packet forwards
        with dp.commit_lock:
            dp.builder.set_ml_model(
                proto_model(flag_thresh=(1 << 30), action="drop"))
            dp.swap()
        res2 = dp.process(pv, now=2)
        cause2 = np.asarray(res2.drop_cause)
        assert int(cause2[0]) == DROP_ACL
        assert int(cause2[1]) == 0
        assert int(res2.stats.tx) == 1

    def test_ml_drop_does_not_install_session(self):
        """An ml-dropped first packet must not open a reflective
        return hole."""
        model = proto_model(flag_thresh=-1, action="drop")
        dp, uplink = build_dp("enforce", model, rules=[_permit_all()])
        fwd = make_packet_vector([
            dict(src="10.1.1.2", dst="172.16.0.9", proto=6,
                 sport=5000, dport=80, rx_if=1)], n=8)
        res = dp.process(fwd, now=1)
        assert int(res.stats.ml_drops) == 1
        assert int(jnp.sum(dp.tables.sess_valid)) == 0


# --------------------------------------------------------------------
# fastpath interplay: the fast tier still scores, bit-exactly
# --------------------------------------------------------------------


class TestFastpathInterplay:
    def _established_batch(self, ml_stage, action="drop", thresh=1):
        # sessions prime under a never-flagging model; the aggressive
        # model swaps in afterward (enforce would drop the priming
        # traffic and install nothing — by design)
        dp, uplink = build_dp(
            ml_stage, proto_model(flag_thresh=(1 << 30)),
            rules=[_permit_all()])
        fwd = make_packet_vector([
            dict(src=f"10.1.1.{2 + i}", dst=f"172.16.0.{10 + i}",
                 proto=6, sport=5000 + i, dport=80, rx_if=1)
            for i in range(6)], n=16)
        dp.process(fwd, now=50)
        with dp.commit_lock:
            dp.builder.set_ml_model(
                proto_model(flag_thresh=thresh, action=action))
            dp.swap()
        reply = make_packet_vector([
            dict(src=f"172.16.0.{10 + i}", dst=f"10.1.1.{2 + i}",
                 proto=6, sport=80, dport=5000 + i, rx_if=uplink)
            for i in range(6)], n=16)
        return dp, reply

    @pytest.mark.slow  # ~14 s: fastpath x ML cross-layer compile; ML scoring/quantization correctness stays fast in this file
    def test_fast_tier_scores_and_enforces(self):
        """All-established batch: the auto dispatcher takes the
        classify-free kernel (fastpath == 1) AND still runs the model
        — counters and verdicts bit-exact vs the forced full chain."""
        dp, reply = self._established_batch("enforce", thresh=1)
        res_auto = dp.process(reply, now=57)
        assert int(res_auto.stats.fastpath) == 1
        assert int(res_auto.stats.ml_scored) == 6
        assert int(res_auto.stats.ml_drops) == 6  # TCP proto 6 > 1
        # forced full chain on identical input/tables: same verdicts
        from vpp_tpu.pipeline.graph import make_pipeline_step

        step_full = make_pipeline_step(
            dp.classifier_impl, dp._skip_local, fast=False,
            ml_mode="enforce")
        res_full = step_full(dp.tables, reply, jnp.int32(57))
        np.testing.assert_array_equal(
            np.asarray(res_auto.drop_cause),
            np.asarray(res_full.drop_cause))
        np.testing.assert_array_equal(
            np.asarray(res_auto.ml_flagged),
            np.asarray(res_full.ml_flagged))
        assert int(res_full.stats.ml_drops) == 6
        assert int(res_full.stats.fastpath) == 0

    def test_fast_tier_age_feature_matches_full_chain(self):
        """The session-age feature is captured pre-touch on BOTH
        tiers: a model keyed on age scores identically through the
        fast kernel and the full chain."""
        # score = age bucket: w1 picks feature 16
        w1 = np.zeros((ML_FEATURES, 2), np.int8)
        w1[16, 0] = 1
        model = MlModel(
            kind="mlp", version=1, n_features=ML_FEATURES, w1=w1,
            b1=np.zeros(2, np.int32), s1=0,
            w2=np.array([1, 0], np.int8), b2=0,
            flag_thresh=5, action="drop").validate()
        dp, uplink = build_dp("enforce", model, rules=[_permit_all()])
        fwd = make_packet_vector([
            dict(src="10.1.1.2", dst="172.16.0.9", proto=6,
                 sport=5000, dport=80, rx_if=1)], n=8)
        dp.process(fwd, now=10)
        reply = make_packet_vector([
            dict(src="172.16.0.9", dst="10.1.1.2", proto=6, sport=80,
                 dport=5000, rx_if=uplink)], n=8)
        # age 3 at now=13: below threshold, forwarded via fast tier
        res = dp.process(reply, now=13)
        assert int(res.stats.fastpath) == 1
        assert int(res.stats.ml_drops) == 0 and int(res.stats.tx) == 1
        # age 9 at now=22 (touch above refreshed to 13): flagged+dropped
        res2 = dp.process(reply, now=22)
        assert int(res2.stats.fastpath) == 1
        assert int(res2.stats.ml_drops) == 1


# --------------------------------------------------------------------
# epoch-swap plane reuse + staging rollback + packed aux riders
# --------------------------------------------------------------------


class TestEpochSwap:
    def test_acl_churn_reuses_model_planes_by_identity(self):
        model = proto_model()
        dp, uplink = build_dp("enforce", model, rules=[_permit_all()])
        before = {f: getattr(dp.tables, f)
                  for f in ("glb_ml_w1", "glb_ml_b1", "glb_ml_w2",
                            "glb_ml_f_leaf", "glb_ml_thresh")}
        with dp.commit_lock:
            dp.builder.set_global_table(
                [_deny_rule("203.0.113.0/24"), _permit_all()])
            dp.swap()
        for f, arr in before.items():
            assert getattr(dp.tables, f) is arr, \
                f"{f} re-shipped on an ACL-only churn"
        # a model churn DOES replace the planes (and only then)
        with dp.commit_lock:
            dp.builder.set_ml_model(proto_model(version=2))
            dp.swap()
        assert dp.tables.glb_ml_w1 is not before["glb_ml_w1"]
        assert int(dp.tables.glb_ml_version) == 2

    def test_state_snapshot_restores_ml_staging(self):
        dp, uplink = build_dp("enforce", proto_model(version=1))
        snap = dp.builder.state_snapshot()
        dp.builder.set_ml_model(proto_model(version=9))
        assert int(dp.builder.ml["glb_ml_version"]) == 9
        dp.builder.state_restore(snap)
        assert int(dp.builder.ml["glb_ml_version"]) == 1
        assert dp.builder.ml_kind == 1

    def test_no_model_staged_keeps_stage_off(self):
        """score/enforce knob with no model: the stage re-gates off —
        no scoring, no counters moving."""
        dp, uplink = build_dp("enforce", model=None)
        assert dp._ml_mode == "off"
        res = dp.process(rand_traffic(16, uplink, seed=2), now=1)
        assert int(res.stats.ml_scored) == 0
        # staging a model flips the gate at the swap
        with dp.commit_lock:
            dp.builder.set_ml_model(proto_model())
            dp.swap()
        assert dp._ml_mode == "enforce"
        res = dp.process(rand_traffic(16, uplink, seed=2), now=2)
        assert int(res.stats.ml_scored) == 16

    def test_capacity_refusal_leaves_staging_intact(self):
        dp, uplink = build_dp("enforce", proto_model(version=1),
                              ml_hidden=4)
        too_big = MlModel(
            kind="mlp", version=2, n_features=ML_FEATURES,
            w1=np.zeros((ML_FEATURES, 8), np.int8),
            b1=np.zeros(8, np.int32), s1=0,
            w2=np.zeros(8, np.int8), b2=0).validate()
        with pytest.raises(MlModelError):
            dp.builder.set_ml_model(too_big)
        assert int(dp.builder.ml["glb_ml_version"]) == 1


class TestPackedAux:
    def test_packed_aux_carries_ml_verdicts(self):
        from vpp_tpu.pipeline.dataplane import (
            PACKED_AUX_ROWS,
            PACKED_AUX_SCHEMA,
            pack_packet_columns,
            packed_input_zeros,
        )

        model = proto_model(action="drop")
        dp, uplink = build_dp("enforce", model, rules=[_permit_all()])
        pv = make_packet_vector(
            [dict(src=f"198.18.0.{i}", dst=f"10.1.1.{2 + i}",
                  proto=17, sport=53, dport=9000 + i, rx_if=uplink)
             for i in range(5)]
            + [dict(src=f"198.19.0.{i}", dst=f"10.1.1.{2 + i}",
                    proto=6, sport=443, dport=9100 + i, rx_if=uplink)
               for i in range(3)], n=16)
        flat = packed_input_zeros(16)
        cols = {f: np.asarray(getattr(pv, f))
                for f in ("src_ip", "dst_ip", "proto", "sport",
                          "dport", "ttl", "pkt_len", "rx_if", "flags")}
        pack_packet_columns(flat.view(np.uint32), cols, 16)
        out, aux = dp.process_packed(flat, now=3, with_aux=True)
        aux_h = np.asarray(aux)
        # width comes from the ONE schema constant (ISSUE 11): the
        # rows are addressed by name, so the next widening is an edit
        # to PACKED_AUX_SCHEMA, not to this test
        assert aux_h.shape == (PACKED_AUX_ROWS,) \
            == (len(PACKED_AUX_SCHEMA),)
        assert aux_h[PACKED_AUX_SCHEMA.index("ml_scored")] == 8
        assert aux_h[PACKED_AUX_SCHEMA.index("ml_flagged")] == 5
        assert aux_h[PACKED_AUX_SCHEMA.index("ml_drops")] == 5


# --------------------------------------------------------------------
# artifact + loader refusals
# --------------------------------------------------------------------


class TestArtifact:
    def test_bad_magic_version_and_corrupt_json(self, tmp_path):
        good = proto_model().to_dict()
        bad_magic = dict(good, format="not-a-model")
        bad_ver = dict(good, format_version=99)
        for doc, frag in ((bad_magic, "magic"), (bad_ver, "format_version")):
            p = tmp_path / "bad.json"
            p.write_text(json.dumps(doc))
            with pytest.raises(MlModelError) as ei:
                load_model(str(p))
            assert frag in str(ei.value)
        p = tmp_path / "torn.json"
        p.write_text(json.dumps(good)[: 40])  # torn mid-document
        with pytest.raises(MlModelError):
            load_model(str(p))

    def test_shape_validation(self):
        with pytest.raises(MlModelError):
            MlModel(kind="mlp", n_features=4,
                    w1=np.zeros((3, 2), np.int8),
                    b1=np.zeros(2, np.int32),
                    w2=np.zeros(2, np.int8)).validate()
        with pytest.raises(MlModelError):
            MlModel(kind="forest", n_features=4,
                    f_feat=np.array([[9]], np.int32),  # out of range
                    f_thresh=np.zeros((1, 1), np.int32),
                    f_leaf=np.zeros((1, 2), np.int32)).validate()
        with pytest.raises(MlModelError):
            MlModel(kind="mlp", n_features=1,
                    w1=np.zeros((1, 1), np.int8),
                    b1=np.zeros(1, np.int32),
                    w2=np.zeros(1, np.int8),
                    action="explode").validate()


class TestLoader:
    def test_refusal_keeps_previous_model_serving(self, tmp_path):
        from vpp_tpu.ml.loader import MlModelSource

        dp, uplink = build_dp("enforce", model=None)
        path = tmp_path / "model.json"
        save_model(proto_model(version=1), str(path))
        src = MlModelSource(dp, str(path))
        assert src.poll() is True
        assert dp._ml_mode == "enforce"
        assert int(dp.tables.glb_ml_version) == 1
        # corrupt overwrite: refused, counted, previous keeps serving
        path.write_text("{ not json")
        assert src.poll() is False
        st = src.stats_snapshot()
        assert st["degraded"] and st["outcomes"]["corrupt"] == 1
        assert int(dp.tables.glb_ml_version) == 1
        assert dp._ml_mode == "enforce"
        # a good v2 heals
        save_model(proto_model(version=2), str(path))
        assert src.poll() is True
        st = src.stats_snapshot()
        assert not st["degraded"] and st["outcomes"]["loaded"] == 2
        assert int(dp.tables.glb_ml_version) == 2
        # unchanged mtime: poll is a no-op stat()
        assert src.poll() is False


class TestShowMl:
    def test_show_ml_page(self, tmp_path):
        from vpp_tpu.cli import DebugCLI
        from vpp_tpu.ml.loader import MlModelSource
        from vpp_tpu.stats.collector import StatsCollector

        dp, uplink = build_dp(
            "enforce", proto_model(version=4, action="ratelimit",
                                   rl_shift=2),
            rules=[_permit_all()])
        coll = StatsCollector(dp)
        res = dp.process(make_packet_vector(
            [dict(src="198.18.0.1", dst="10.1.1.2", proto=17,
                  sport=53, dport=9000, rx_if=uplink)], n=8))
        coll.update(res.stats)
        path = tmp_path / "m.json"
        path.write_text("garbage")
        src = MlModelSource(dp, str(path))
        src.poll()
        cli = DebugCLI(dp, stats=coll, ml_source=src)
        page = cli.run("show ml")
        assert "ml stage: enforce" in page
        assert "model mlp" in page
        assert "v4" in page and "ratelimit" in page
        assert "admit 1/4" in page
        assert "scored 1" in page and "flagged 1" in page
        assert "DEGRADED" in page and "corrupt 1" in page
        assert "show ml" in cli.run("help")

    def test_show_ml_without_model(self):
        from vpp_tpu.cli import DebugCLI

        dp, uplink = build_dp("score", model=None)
        page = DebugCLI(dp).run("show ml")
        assert "ml stage: off (knob score, model none)" in page
        assert "no model staged" in page


class TestAgentWiring:
    def test_yaml_config_to_scoring_epoch(self, tmp_path):
        """ml_model_path in the agent YAML: the artifact publishes at
        start (before traffic), the maintenance tick hot-reloads on
        mtime change, and the collector exports the ML surface."""
        from vpp_tpu.cmd.agent import ContivAgent
        from vpp_tpu.cmd.config import AgentConfig

        mpath = tmp_path / "model.json"
        save_model(proto_model(version=3), str(mpath))
        cfg = AgentConfig.from_dict({
            "node_name": "n1",
            "serve_http": False,
            "ml_model_path": str(mpath),
            "dataplane": {"sess_slots": 256, "ml_stage": "enforce",
                          "ml_hidden": 4},
        })
        a = ContivAgent(cfg)
        a.start()
        try:
            assert a.dataplane._ml_mode == "enforce"
            assert int(a.dataplane.tables.glb_ml_version) == 3
            # hot reload: v5 overwrite + a maintenance tick
            save_model(proto_model(version=5), str(mpath))
            import os

            os.utime(str(mpath), (1, 2 << 30))  # force mtime change
            a.maintenance_tick()
            assert int(a.dataplane.tables.glb_ml_version) == 5
            text = a.stats.registry.render("/stats")
            assert 'vpp_tpu_ml_stage{mode="enforce"} 1' in text
            assert "vpp_tpu_ml_model_version 5" in text
            assert 'vpp_tpu_degraded{component="ml"} 0' in text
            assert 'vpp_tpu_ml_load_total{outcome="loaded"} 2' in text
        finally:
            a.close()
