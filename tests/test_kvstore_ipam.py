"""Tests for kvstore (watch/CAS/persist), KVProxy, IPAM and node-ID allocator.

Mirrors reference tests: plugins/contiv/ipam/ipam_test.go (arithmetic +
allocation), persist_test.go (reload), kvdbproxy tests (self-echo skip).
"""


import pytest

from vpp_tpu.agent.node_id import NodeIDAllocator
from vpp_tpu.ipam import IPAM, IpamConfig
from vpp_tpu.kvstore import Broker, KVProxy, KVStore, Op


def test_kvstore_watch_and_cas():
    s = KVStore()
    events = []
    cancel = s.watch("a/", events.append)
    s.put("a/x", 1)
    s.put("b/y", 2)  # outside prefix
    s.delete("a/x")
    assert [(e.op, e.key, e.value) for e in events] == [
        (Op.PUT, "a/x", 1),
        (Op.DELETE, "a/x", None),
    ]
    cancel()
    s.put("a/z", 3)
    assert len(events) == 2

    assert s.compare_and_put("c", None, 10)
    assert not s.compare_and_put("c", None, 11)  # exists now
    assert s.compare_and_put("c", 10, 12)
    assert s.get("c") == 12


def test_kvstore_persistence(tmp_path):
    path = str(tmp_path / "kv.json")
    s = KVStore(persist_path=path)
    s.put("k8s/pod/default/p1", {"ip": "10.1.1.2"})
    s.put("ipam/p1", {"ip": 123, "pod": "p1"})
    s.save()  # autosave is debounced; explicit save = checkpoint

    s2 = KVStore(persist_path=path)
    assert s2.get("k8s/pod/default/p1") == {"ip": "10.1.1.2"}
    assert s2.revision == s.revision


def test_broker_prefix_scoping():
    s = KVStore()
    b = Broker(s, "/vnf-agent/node1/")
    b.put("contiv/x", 1)
    assert s.get("/vnf-agent/node1/contiv/x") == 1
    events = []
    b.watch("contiv/", events.append)
    b.put("contiv/y", 2)
    assert events[0].key == "contiv/y"  # prefix stripped


def test_kvproxy_skips_self_echo():
    s = KVStore()
    proxy = KVProxy(s)
    events = []
    proxy.watch("cfg/", events.append)
    proxy.put("cfg/mine", 1)            # self write -> echo swallowed
    s.put("cfg/other", 2)               # external write -> delivered
    proxy.put("cfg/loud", 3, ignore_echo=False)
    assert [e.key for e in events] == ["cfg/other", "cfg/loud"]


def test_ipam_network_arithmetic():
    """Reference example (ipam/doc.go): node 5 with defaults:
    pods 10.1.5.0/24, host interconnect 172.30.5.0/24, node IP .5."""
    ipam = IPAM(node_id=5)
    assert str(ipam.pod_network) == "10.1.5.0/24"
    assert str(ipam.pod_gateway_ip()) == "10.1.5.1"
    assert str(ipam.vpp_host_network) == "172.30.5.0/24"
    assert str(ipam.veth_vpp_end_ip()) == "172.30.5.1"
    assert str(ipam.veth_host_end_ip()) == "172.30.5.2"
    assert str(ipam.node_ip_address()) == "192.168.16.5"
    assert str(ipam.vxlan_ip_address()) == "192.168.30.5"
    assert str(ipam.other_node_pod_network(7)) == "10.1.7.0/24"
    assert str(ipam.node_ip_address(7)) == "192.168.16.7"


def test_ipam_allocation_cycle():
    ipam = IPAM(node_id=1)
    ip1 = ipam.next_pod_ip("default/p1")
    ip2 = ipam.next_pod_ip("default/p2")
    assert str(ip1) == "10.1.1.2"  # .1 is the gateway
    assert str(ip2) == "10.1.1.3"
    assert ipam.get_pod_ip("default/p1") == ip1
    assert ipam.release_pod_ip("default/p1")
    assert not ipam.release_pod_ip("default/p1")  # already released
    # released IP is not immediately reused (rotation)
    ip3 = ipam.next_pod_ip("default/p3")
    assert str(ip3) == "10.1.1.4"
    with pytest.raises(ValueError):
        ipam.next_pod_ip("")


def test_ipam_exhaustion_and_wrap():
    cfg = IpamConfig(pod_network_prefix_len=29)  # 8 addrs: usable seq 2..6
    # (0=network, 1=gateway, 7=broadcast are reserved)
    ipam = IPAM(node_id=1, config=cfg)
    ips = [ipam.next_pod_ip(f"p{i}") for i in range(5)]
    assert len(set(ips)) == 5
    with pytest.raises(RuntimeError):
        ipam.next_pod_ip("overflow")
    ipam.release_pod_ip("p0")
    assert ipam.next_pod_ip("again") == ips[0]


def test_ipam_persistence_reload():
    store = KVStore()
    broker = Broker(store, "/vnf-agent/node1/")
    ipam = IPAM(node_id=1, broker=broker)
    ip1 = ipam.next_pod_ip("default/p1")
    ip2 = ipam.next_pod_ip("default/p2")
    ipam.release_pod_ip("default/p1")

    # Agent restart: new IPAM instance over the same store.
    ipam2 = IPAM(node_id=1, broker=broker)
    assert ipam2.get_pod_ip("default/p2") == ip2
    assert ipam2.get_pod_ip("default/p1") is None
    # lastAssigned was restored: allocation continues past p2.
    ip3 = ipam2.next_pod_ip("default/p3")
    assert int(ip3) > int(ip2)


def test_node_id_allocator():
    store = KVStore()
    a1 = NodeIDAllocator(store, "node-a")
    a2 = NodeIDAllocator(store, "node-b")
    assert a1.get_or_allocate() == 1
    assert a2.get_or_allocate() == 2
    # restart of node-a reuses its claim
    a1b = NodeIDAllocator(store, "node-a")
    assert a1b.get_or_allocate() == 1

    a1.publish_ips("192.168.16.1/24", "10.0.0.1")
    nodes = a2.list_nodes()
    assert nodes[1]["ip"] == "192.168.16.1/24"
    assert nodes[1]["name"] == "node-a"

    a2.release()
    a3 = NodeIDAllocator(store, "node-c")
    assert a3.get_or_allocate() == 2  # freed ID is reused


def test_ipam_never_allocates_broadcast():
    cfg = IpamConfig(pod_network_prefix_len=30)  # 4 addrs: only seq 2 usable
    ipam = IPAM(node_id=1, config=cfg)
    ip = ipam.next_pod_ip("p0")
    assert int(ip) % 4 == 2  # not network(0), gateway(1), broadcast(3)
    with pytest.raises(RuntimeError):
        ipam.next_pod_ip("p1")


def test_ipam_rejects_node_id_overflow():
    # /16 subnet with /20 per-node networks leaves 4 node bits -> IDs 0..15.
    cfg = IpamConfig(pod_subnet_cidr="10.1.0.0/16", pod_network_prefix_len=20)
    with pytest.raises(ValueError):
        IPAM(node_id=17, config=cfg)


def test_kvproxy_ignore_consumed_without_watchers():
    """An ignore entry must be consumed by the echo even when no watcher
    matches, so it cannot swallow a later external change."""
    s = KVStore()
    proxy = KVProxy(s)
    proxy.put("cfg/x", 1)  # echo consumed with no subscribers
    events = []
    proxy.watch("cfg/", events.append)
    s.put("cfg/x", 2)  # external change must be delivered
    assert [e.value for e in events] == [2]


def test_kvproxy_two_watchers_one_skip():
    s = KVStore()
    proxy = KVProxy(s)
    ev1, ev2 = [], []
    proxy.watch("cfg/", ev1.append)
    proxy.watch("cfg/", ev2.append)
    proxy.put("cfg/self", 1)
    s.put("cfg/ext", 2)
    assert [e.key for e in ev1] == ["cfg/ext"]
    assert [e.key for e in ev2] == ["cfg/ext"]
