"""Worker process for tests/test_multihost.py: one JAX process of a
2-process cluster mesh (run directly, never imported by pytest).

Builds its LOCAL nodes of a 4-node cluster (2 virtual CPU devices per
process), publishes tables collectively, steps the fabric in lockstep,
and prints one JSON verdict line the parent asserts on.
"""

import json
import os
import sys

PROC_ID = int(sys.argv[1])
NUM_PROCS = int(sys.argv[2])
PORT = sys.argv[3]

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from vpp_tpu.parallel.multihost import (  # noqa: E402
    MultiHostCluster, barrier, init_multihost,
)
from mh_common import pod_ips, stage_full_mesh  # noqa: E402
from vpp_tpu.ir.rule import Action, ContivRule, Protocol  # noqa: E402
from vpp_tpu.pipeline.tables import DataplaneConfig  # noqa: E402
from vpp_tpu.pipeline.vector import Disposition  # noqa: E402
import ipaddress  # noqa: E402

init_multihost(f"127.0.0.1:{PORT}", NUM_PROCS, PROC_ID,
               heartbeat_timeout_s=600)

N_NODES = 4
cfg = DataplaneConfig(
    max_tables=4, max_rules=16, max_global_rules=32, max_ifaces=8,
    fib_slots=32, sess_slots=256, nat_mappings=4, nat_backends=16,
)
cluster = MultiHostCluster(N_NODES, cfg)
assert cluster.local_nodes == ([0, 1] if PROC_ID == 0 else [2, 3]), \
    cluster.local_nodes

pod_if = stage_full_mesh(cluster)
# node 3 additionally carries a deny-all-but-TCP/80 global table:
# fabric traffic enters through its uplink and must be filtered
if 3 in cluster.local_nodes:
    cluster.node(3).builder.set_global_table([
        ContivRule(action=Action.PERMIT, protocol=Protocol.TCP,
                   dest_port=80),
        ContivRule(action=Action.DENY),
    ])

barrier("staged")
cluster.publish()

all_pod_ip = pod_ips(N_NODES)

# lockstep step 1: pod0 (P0) -> pod2 (P1) allowed; pod1 -> pod3:80
# allowed; pod1 -> pod3:22 denied by node 3's global table
frames = [[] for _ in cluster.local_nodes]
if PROC_ID == 0:
    frames[0] = [dict(src=all_pod_ip[0], dst=all_pod_ip[2], proto=6,
                      sport=1000, dport=8080, rx_if=pod_if[0])]
    frames[1] = [
        dict(src=all_pod_ip[1], dst=all_pod_ip[3], proto=6,
             sport=1001, dport=80, rx_if=pod_if[1]),
        dict(src=all_pod_ip[1], dst=all_pod_ip[3], proto=6,
             sport=1002, dport=22, rx_if=pod_if[1]),
    ]
res = cluster.step(cluster.make_frames(frames, n=8), now=1)

deliv_disp = cluster.local_rows(res.delivered.disp)
deliv_dst = cluster.local_rows(res.delivered.pkts.dst_ip)
deliv_txif = cluster.local_rows(res.delivered.tx_if)
drop_acl = cluster.local_rows(res.stats.drop_acl)

verdict = {"proc": PROC_ID, "local_nodes": cluster.local_nodes}
if PROC_ID == 1:
    # row 0 = node 2, row 1 = node 3 (host-local view)
    n2_local = np.nonzero(deliv_disp[0] == int(Disposition.LOCAL))[0]
    n3_local = np.nonzero(deliv_disp[1] == int(Disposition.LOCAL))[0]
    verdict.update(
        pod2_delivered=len(n2_local),
        pod2_txif_ok=bool((deliv_txif[0][n2_local] == pod_if[2]).all()),
        pod2_dst_ok=bool((deliv_dst[0][n2_local].astype(np.uint32)
                          == int(ipaddress.ip_address(all_pod_ip[2]))
                          ).all()),
        pod3_delivered=len(n3_local),
        node3_acl_drops=int(drop_acl[1]),
    )
else:
    local_disp = cluster.local_rows(res.local.disp)
    verdict.update(
        sent_remote=int((local_disp[0][:1]
                         == int(Disposition.REMOTE)).sum()
                        + (local_disp[1][:2]
                           == int(Disposition.REMOTE)).sum()))

# lockstep step 2: reply path pod2 -> pod0 rides an established-flow
# (session was installed at delivery) — proves sessions persist in the
# global tables across collective steps
frames2 = [[] for _ in cluster.local_nodes]
if PROC_ID == 1:
    frames2[0] = [dict(src=all_pod_ip[2], dst=all_pod_ip[0], proto=6,
                       sport=8080, dport=1000, rx_if=pod_if[2])]
res2 = cluster.step(cluster.make_frames(frames2, n=8), now=2)
if PROC_ID == 0:
    d = cluster.local_rows(res2.delivered.disp)
    verdict["reply_delivered"] = int((d[0] == int(Disposition.LOCAL)).sum())

barrier("done")
print("VERDICT " + json.dumps(verdict), flush=True)
