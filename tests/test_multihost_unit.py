"""LockstepDriver protocol edges, in-process (single-controller JAX —
process_allgather degenerates to identity, so the agreement logic runs
for real without worker subprocesses)."""

import numpy as np
import pytest

from vpp_tpu.ir.rule import Action, ContivRule
from vpp_tpu.kvstore.store import KVStore
from vpp_tpu.parallel.multihost import LockstepDriver, MultiHostCluster
from vpp_tpu.pipeline.tables import DataplaneConfig
from vpp_tpu.pipeline.vector import Disposition


def build_cluster():
    cfg = DataplaneConfig(
        max_tables=4, max_rules=16, max_global_rules=32, max_ifaces=8,
        fib_slots=32, sess_slots=256, nat_mappings=4, nat_backends=16,
    )
    cl = MultiHostCluster(2, cfg)
    for nid in range(2):
        n = cl.node(nid)
        up = n.add_uplink()
        pi = n.add_pod_interface(("d", f"p{nid}"))
        n.builder.add_route(f"10.{nid + 1}.0.2/32", pi,
                            Disposition.LOCAL)
        other = 1 - nid
        n.builder.add_route(f"10.{other + 1}.0.0/24", up,
                            Disposition.REMOTE, node_id=other)
    return cl


def frames(cl, sport=1000):
    f = [[] for _ in cl.local_nodes]
    f[0] = [dict(src="10.1.0.2", dst="10.2.0.2", proto=6, sport=sport,
                 dport=80, rx_if=cl.node(0).pod_if[("d", "p0")])]
    return f


@pytest.mark.slow  # ~15 s of collective ticks; the baseline-agreement
# logic it pins is byte-identical in the in-process driver the other
# (tier-1) cases here exercise
def test_stale_stop_counter_does_not_halt_a_new_fleet():
    """A stop agreed by a PREVIOUS deployment persists in the store;
    the new fleet's driver must baseline it away — and a FRESH stop
    request still stops."""
    store = KVStore()
    store.put("/mesh/epoch/stop_req", 5)     # old fleet's shutdown
    cl = build_cluster()
    driver = LockstepDriver(cl, store)
    cl.publish()
    res = driver.tick(frames(cl), n=8)
    assert res is not None, "stale stop halted a restarted fleet"
    driver.request_stop()
    assert driver.tick(frames(cl), n=8) is None
    # post-stop: no further collectives may be issued
    assert driver.tick(frames(cl), n=8) is None


@pytest.mark.slow  # ~15 s: multi-round agreement loop; single-commit agreement is covered by the fast tests in this file
def test_commit_agreement_publishes_once_per_request():
    store = KVStore()
    cl = build_cluster()
    driver = LockstepDriver(cl, store)
    cl.publish()
    assert cl.epoch == 1
    driver.tick(frames(cl), n=8)
    assert cl.epoch == 1                     # no request, no publish

    cl.node(1).builder.set_global_table([ContivRule(action=Action.DENY)])
    driver.request_commit()
    res = driver.tick(frames(cl, sport=2000), n=8)
    assert cl.epoch == 2                     # agreed, published
    # the SAME tick already enforces the new epoch
    disp = np.asarray(cl.local_rows(res.delivered.disp))
    assert not (disp[1] == int(Disposition.LOCAL)).any()
    driver.tick(frames(cl, sport=3000), n=8)
    assert cl.epoch == 2                     # one publish per request


def test_idle_skip_and_commit_tick_always_steps():
    store = KVStore()
    cl = build_cluster()
    driver = LockstepDriver(cl, store)
    cl.publish()
    calls = []

    def fabric(tick):
        calls.append(tick)
        return "stepped"

    assert driver.tick_fabric(fabric, has_work=False) is None
    assert driver.tick_fabric(fabric, has_work=False) is None
    assert calls == [], "idle fleet must skip the fabric step"
    assert driver.ticks == 2, "ticks advance even when idle"

    assert driver.tick_fabric(fabric, has_work=True) == "stepped"
    assert calls == [3]

    driver.request_commit()
    assert driver.tick_fabric(fabric, has_work=False) == "stepped", \
        "a commit tick must step even when idle"
    assert driver.applied == 1

    driver.request_stop()
    out = driver.tick_fabric(fabric, has_work=True)
    assert out is LockstepDriver._STOPPED
    assert calls == [3, 4], "no step after the fleet agreed to stop"


def test_session_aging_on_tick_cadence():
    store = KVStore()
    cl = build_cluster()
    driver = LockstepDriver(cl, store, expire_every=2)
    cl.publish()
    driver.tick(frames(cl), n=8)             # installs a session
    occupied = int(np.asarray(cl.tables.sess_valid).sum())
    assert occupied > 0
    # tick 2 triggers the collective expiry pass; with a huge max_age
    # nothing is reclaimed (no-op correctness), with max_age tiny the
    # slots free
    driver.tick([[] for _ in cl.local_nodes], n=8)
    assert int(np.asarray(cl.tables.sess_valid).sum()) == occupied
    cl.expire_sessions(now=10_000_000, max_age=1)
    assert int(np.asarray(cl.tables.sess_valid).sum()) == 0


@pytest.mark.slow  # ~46 s: full fleet-wide rung agreement; commit/publish correctness stays fast via the smaller agreement tests below
def test_publish_agrees_fib_rung_fleet_wide():
    """The widened 6-column selection allgather: publish folds every
    process's lpm eligibility (min) and staged route count (max) into
    one fleet-agreed FIB rung, and the next tick runs it."""
    cfg = DataplaneConfig(
        max_tables=4, max_rules=16, max_global_rules=32, max_ifaces=8,
        fib_slots=32, sess_slots=256, nat_mappings=4, nat_backends=16,
        fib_lpm_min_routes=4,
    )
    cl = MultiHostCluster(2, cfg)
    for nid in range(2):
        n = cl.node(nid)
        up = n.add_uplink()
        pi = n.add_pod_interface(("d", f"p{nid}"))
        n.builder.add_route(f"10.{nid + 1}.0.2/32", pi, Disposition.LOCAL)
        other = 1 - nid
        n.builder.add_route(f"10.{other + 1}.0.0/24", up,
                            Disposition.REMOTE, node_id=other)
        n.builder.add_route("10.8.0.0/16", up, Disposition.REMOTE,
                            node_id=other)
        n.builder.add_route(f"10.8.{nid}.0/24", pi, Disposition.LOCAL)
    assert cl.fib_impl == "dense"            # pre-publish default
    cl.publish()
    assert cl.fib_impl == "lpm"              # 4 routes >= the floor
    driver = LockstepDriver(cl, KVStore())
    res = driver.tick(frames(cl), n=8)
    disp = np.asarray(cl.local_rows(res.delivered.disp))
    assert (disp[1] == int(Disposition.LOCAL)).sum() == 1

    # below the floor the fleet stays dense (the standalone ladder)
    cl2 = build_cluster()                    # 2 routes/node, floor 256
    cl2.publish()
    assert cl2.fib_impl == "dense"


def test_publish_names_out_of_mesh_targets():
    cl = build_cluster()
    cl.node(0).builder.add_route("10.77.0.0/24", cl.node(0).uplink_if,
                                 Disposition.REMOTE, node_id=7)
    with pytest.raises(ValueError, match="outside"):
        cl.publish()


def test_publish_guards_missing_uplink():
    """An in-mesh fabric target without an uplink would silently drop
    inbound traffic on reserved interface 0 — publish refuses."""
    cfg = DataplaneConfig(
        max_tables=4, max_rules=16, max_global_rules=32, max_ifaces=8,
        fib_slots=32, sess_slots=256, nat_mappings=4, nat_backends=16,
    )
    cl = MultiHostCluster(2, cfg)
    up0 = cl.node(0).add_uplink()
    cl.node(0).builder.add_route("10.2.0.0/24", up0,
                                 Disposition.REMOTE, node_id=1)
    # node 1: no add_uplink()
    with pytest.raises(ValueError, match="no uplink"):
        cl.publish()
