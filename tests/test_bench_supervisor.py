"""bench.py supervisor salvage: a wedged-TPU partial + CPU fill must
merge into one driver JSON with TPU sections winning and provenance
recorded (the r3 failure mode — a mid-run tunnel wedge recording
NOTHING — must be structurally impossible)."""

import importlib.util
import os
import sys

_spec = importlib.util.spec_from_file_location(
    "bench", os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "bench.py"))
bench = importlib.util.module_from_spec(_spec)
sys.modules.setdefault("bench", bench)
_spec.loader.exec_module(bench)


CPU_RES = {
    "metric": bench.METRIC, "value": 0.3, "unit": "Mpps",
    "vs_baseline": 0.0075,
    "details": {
        "backend": "cpu", "host_cores": 1, "rules": 10240,
        "frame_latency_p50_us": 1200.0,
        "pod_to_pod_fwd_mpps": 0.4,
        "io_daemon_veth_mpps": 0.08,
        "commit_ms_global_table": 31.0,
    },
}


def test_tpu_sections_win_and_provenance_listed():
    tpu_part = {
        "backend": "tpu", "host_cores": 1, "started_at": "t",
        "load_at_start": 0.1, "probe_attempt": 1,
        "headline_mpps": 171.2, "rules": 10240,
        "frame_latency_p50_us": 370.0,
    }
    out = bench._merge_salvage(tpu_part, CPU_RES, stalled=True)
    assert out["value"] == 171.2                    # TPU headline kept
    assert out["vs_baseline"] == round(171.2 / 40.0, 4)
    d = out["details"]
    assert d["backend"] == "tpu"
    assert d["frame_latency_p50_us"] == 370.0       # TPU wins over CPU
    assert d["io_daemon_veth_mpps"] == 0.08         # CPU filled the gap
    # provenance: exactly the CPU-only sections, no meta keys
    assert d["cpu_filled_sections"] == [
        "commit_ms_global_table", "io_daemon_veth_mpps",
        "pod_to_pod_fwd_mpps"]
    assert "stalled (tunnel wedge)" in d["supervisor"]
    assert "headline_mpps" not in d


def test_no_tpu_partial_falls_back_to_cpu_result():
    out = bench._merge_salvage({}, CPU_RES, stalled=False)
    assert out["value"] == 0.3
    d = out["details"]
    assert d["backend"] == "cpu"
    assert "cpu_filled_sections" not in d
    assert "tpu sections salvaged: 0" in d["supervisor"]


def test_cpu_fill_also_dead_still_emits_json():
    tpu_part = {"backend": "tpu", "headline_mpps": 150.0}
    out = bench._merge_salvage(tpu_part, None, stalled=True)
    assert out["value"] == 150.0
    assert out["details"]["backend"] == "tpu"

    out = bench._merge_salvage({}, None, stalled=True)
    assert out["value"] == 0.0
    assert out["metric"] == bench.METRIC


def test_stalled_cpu_fill_salvages_its_own_sidecar():
    """Fill run killed too: its sidecar sections (and an inner partial
    that had already fallen back to CPU) must still reach the output."""
    inner_cpu_partial = {"backend": "cpu", "headline_mpps": 0.31,
                         "frame_latency_p50_us": 1100.0}
    fill_sidecar = {"backend": "cpu", "headline_mpps": 0.29,
                    "frame_latency_p50_us": 1050.0,
                    "pod_to_pod_fwd_mpps": 0.4}
    out = bench._merge_salvage(inner_cpu_partial, None, stalled=True,
                               cpu_side=fill_sidecar)
    d = out["details"]
    assert out["value"] == 0.29          # freshest CPU headline
    assert d["pod_to_pod_fwd_mpps"] == 0.4
    assert d["frame_latency_p50_us"] == 1050.0
