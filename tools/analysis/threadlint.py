"""The ``--threads`` lock-discipline pass.

Scope: the concurrent control-plane and pump modules (io/pump.py,
io/cluster_pump.py, io/rings.py, io/daemon.py, kvstore/, stats/,
trace/, pipeline/txn.py, pipeline/persistent.py — the files where the
agent's threads, the pump's fetch workers, the device-ring
stager/fetcher pair and the kvstore's replication threads meet shared
state).

Rules (docs/STATIC_ANALYSIS.md catalog):

* ``unlocked-access`` — per class, the PROTECTED attribute set is
  inferred: any ``self.X`` written under ``with self.<lock>`` in a
  non-``__init__`` method is protected by that lock; every other
  read/write of X in any method must then hold the same lock.
  Exemptions: ``__init__`` (no concurrent access before publication),
  methods whose name ends in ``_locked`` (the in-tree convention for
  "caller holds the lock"), and sites annotated
  ``# unlocked: <reason>``.
* ``lock-order``      — per class, ``with self.A:`` lexically nested
  inside ``with self.B:`` defines the acquisition edge B->A; a cycle
  in that graph (A->B somewhere, B->A elsewhere) is a deadlock-by-
  schedule waiting to happen.

Lock attributes are discovered from ``__init__``: names assigned
``threading.Lock()``, ``RLock()`` or ``Condition()`` (aliases via
``self.a = self.b`` follow the aliased lock). Nested function bodies
(worker closures handed to threads) reset the held-lock context — the
closure runs later, not under the ``with`` that lexically encloses its
definition.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, List, Optional, Set, Tuple

from analysis.common import Finding, iter_source_files, parse_suppressions

THREAD_ROOTS = (
    "vpp_tpu/io/pump.py",
    "vpp_tpu/io/cluster_pump.py",
    # ISSUE 7: the device-ring staging half (DeviceDescRing's cyclic
    # acquire/release races the stager against the fetcher) and the
    # IO daemon's rx/tx threads
    "vpp_tpu/io/rings.py",
    "vpp_tpu/io/daemon.py",
    # ISSUE 13: the latency governor's control state is written by
    # the pump's dispatch-thread ticks and snapshotted by the
    # collector/CLI; the priority filter's dynamic flow marks are
    # written from the ML mirror path
    "vpp_tpu/io/governor.py",
    "vpp_tpu/kvstore",
    "vpp_tpu/stats",
    "vpp_tpu/trace",
    "vpp_tpu/pipeline/txn.py",
    "vpp_tpu/pipeline/persistent.py",
    # tenancy host side (ISSUE 14): the WFQ scheduler/classifier the
    # pump drives under its _held_lock/_lat_lock
    "vpp_tpu/tenancy/sched.py",
    # ISSUE 8: the snapshotter's stats flip under its lock around the
    # long unlocked drain, and the fault plan's spec/counter state is
    # bumped from every thread that crosses an armed point
    "vpp_tpu/pipeline/snapshot.py",
    "vpp_tpu/testing/faults.py",
    # ISSUE 10: the ML model source's load ledger is written by the
    # maintenance thread and snapshotted by the collector/CLI
    "vpp_tpu/ml/loader.py",
    # ISSUE 11: the telemetry plane's host paths — the rider snapshot
    # is fetcher-written and collector/CLI-read (the device kernels in
    # the same file are thread-free, the pass just sees no classes)
    "vpp_tpu/ops/telemetry.py",
    # ISSUE 18: the fleet tier — steering's route table flips under
    # _lock against lock-free partition() reads, membership wraps
    # kvstore CAS from any thread, and the pump's dispatch/worker
    # threads share the conservation counters
    "vpp_tpu/fleet/steering.py",
    "vpp_tpu/fleet/membership.py",
    "vpp_tpu/io/fleet.py",
)

LOCK_CTORS = {"Lock", "RLock", "Condition"}


def _self_attr(expr) -> Optional[str]:
    """'x' for ``self.x``, 'a.b' for ``self.a.b`` — None otherwise."""
    parts = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name) and expr.id == "self":
        return ".".join(reversed(parts))
    return None


def _is_lock_ctor(expr) -> bool:
    if not isinstance(expr, ast.Call):
        return False
    f = expr.func
    name = f.attr if isinstance(f, ast.Attribute) else \
        (f.id if isinstance(f, ast.Name) else None)
    return name in LOCK_CTORS


class _ClassInfo:
    def __init__(self, node: ast.ClassDef):
        self.node = node
        self.locks: Set[str] = set()
        # attr -> {lock: [(method, line, is_write)]} for locked writes
        self.locked_writes: Dict[str, Dict[str, list]] = {}
        # every access: (attr, method, line, is_write, held_locks)
        self.accesses: List[Tuple[str, str, int, bool, frozenset]] = []
        # lock-nesting edges: (outer, inner) -> first line seen
        self.edges: Dict[Tuple[str, str], int] = {}


class ThreadPass:
    def __init__(self, repo: Path, roots=THREAD_ROOTS):
        self.repo = repo
        self.roots = roots
        self.findings: List[Finding] = []

    def run(self) -> List[Finding]:
        for relpath, path in iter_source_files(self.repo, self.roots):
            src = path.read_text()
            try:
                tree = ast.parse(src, filename=relpath)
            except SyntaxError:
                continue  # the style pass reports parse failures
            sup = parse_suppressions(src, relpath)
            self.findings.extend(sup.problems)
            for node in ast.walk(tree):
                if isinstance(node, ast.ClassDef):
                    self._check_class(relpath, node, sup)
        return self.findings

    def _emit(self, relpath, line, rule, msg, sup) -> None:
        if line in sup.unlocked:
            return
        self.findings.append(Finding(relpath, line, rule, msg))

    # --- per-class analysis ---
    def _check_class(self, relpath: str, cls: ast.ClassDef, sup) -> None:
        info = _ClassInfo(cls)
        init = next((m for m in cls.body
                     if isinstance(m, ast.FunctionDef)
                     and m.name == "__init__"), None)
        if init is not None:
            aliases: Dict[str, str] = {}
            for stmt in ast.walk(init):
                if not isinstance(stmt, ast.Assign):
                    continue
                for t in stmt.targets:
                    attr = _self_attr(t)
                    if attr is None:
                        continue
                    if _is_lock_ctor(stmt.value):
                        info.locks.add(attr)
                    else:
                        src_attr = _self_attr(stmt.value)
                        if src_attr is not None:
                            aliases[attr] = src_attr
            # one alias hop is enough for the in-tree idiom
            # (commit_lock = self._lock)
            for dst, src_attr in aliases.items():
                if src_attr in info.locks:
                    info.locks.add(dst)
        if not info.locks:
            return

        for m in cls.body:
            if isinstance(m, ast.FunctionDef):
                self._scan_method(info, m)

        self._report(relpath, info, sup)

    def _scan_method(self, info: _ClassInfo, method: ast.FunctionDef):
        exempt = (method.name == "__init__"
                  or method.name.endswith("_locked"))

        def visit(node, held: tuple):
            if isinstance(node, ast.With):
                new_held = held
                for item in node.items:
                    attr = _self_attr(item.context_expr)
                    # `with self._lock:` and `with self._cv:` acquire;
                    # `self._cv.wait()` etc handled as accesses below
                    if attr is not None and attr in info.locks:
                        for outer in new_held:
                            if outer != attr:
                                info.edges.setdefault(
                                    (outer, attr), item.context_expr.lineno)
                        new_held = new_held + (attr,)
                    else:
                        visit(item.context_expr, held)
                for s in node.body:
                    visit(s, new_held)
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not method:
                # a nested closure runs later (worker threads): the
                # lexically-enclosing with-blocks are NOT held
                for child in ast.iter_child_nodes(node):
                    visit(child, ())
                return
            if isinstance(node, ast.Attribute):
                attr = _self_attr(node)
                if attr is None:
                    # not a plain self.a(.b) chain — e.g. the base is a
                    # Subscript or Call (`self._buf[0].x`); recurse so
                    # the inner self.* access is still recorded
                    for child in ast.iter_child_nodes(node):
                        visit(child, held)
                    return
                if attr not in info.locks and not exempt:
                    is_write = isinstance(node.ctx,
                                          (ast.Store, ast.Del))
                    info.accesses.append(
                        (attr, method.name, node.lineno, is_write,
                         frozenset(held)))
                    if is_write and held:
                        for lk in held:
                            info.locked_writes.setdefault(
                                attr, {}).setdefault(lk, []).append(
                                (method.name, node.lineno))
                # don't recurse into the attribute chain: self.a.b
                # was recorded as one dotted access
                return
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        visit(method, ())

    def _report(self, relpath: str, info: _ClassInfo, sup) -> None:
        cls = info.node.name
        for attr, by_lock in sorted(info.locked_writes.items()):
            # the protecting lock: the one most of the locked writes
            # hold (ties broken lexicographically for determinism)
            lock = sorted(by_lock,
                          key=lambda lk: (-len(by_lock[lk]), lk))[0]
            for a_attr, meth, line, is_write, held in info.accesses:
                if a_attr != attr or lock in held:
                    continue
                kind = "write" if is_write else "read"
                self._emit(
                    relpath, line, "unlocked-access",
                    f"{cls}.{attr} is written under self.{lock} "
                    f"(lock-protected) but {kind} in {meth}() without "
                    f"it", sup)
        # lock-order cycles: A->B and B->A both observed
        for (a, b), line in sorted(info.edges.items()):
            if (b, a) in info.edges and a < b:
                self._emit(
                    relpath, line, "lock-order",
                    f"{cls}: self.{a} and self.{b} are acquired in "
                    f"both nesting orders (here {a}->{b}, line "
                    f"{info.edges[(b, a)]} {b}->{a}): deadlock by "
                    f"schedule", sup)


def threads_lint(repo=None, roots=THREAD_ROOTS) -> List[Finding]:
    """Run the pass; returns unsuppressed findings (empty == clean)."""
    if repo is None:
        repo = Path(__file__).resolve().parents[2]
    return ThreadPass(Path(repo), roots).run()
