"""Registry/table invariant passes (``--metrics`` / ``--counters`` /
``--tables``) — moved verbatim in behavior from the original
tools/lint.py. These import the dataplane (and therefore jax), so they
only run when asked for; tier-1 invokes them via
tests/test_exposition.py and tests/test_acl_bv.py.
"""

from __future__ import annotations

import sys
from pathlib import Path


def _repo_on_path() -> Path:
    repo = Path(__file__).resolve().parents[2]
    if str(repo) not in sys.path:
        sys.path.insert(0, str(repo))
    return repo


def _build_full_registry():
    """Every family the deployed processes serve, in ONE registry (so
    cross-path duplicates are caught). Shared by the --metrics and
    --counters passes."""
    _repo_on_path()
    from vpp_tpu.ksr.reflector import ReflectorRegistry
    from vpp_tpu.kvstore.server import make_request_histogram
    from vpp_tpu.pipeline.dataplane import Dataplane
    from vpp_tpu.pipeline.tables import DataplaneConfig
    from vpp_tpu.stats.collector import (
        StatsCollector,
        register_control_plane_metrics,
        register_ksr_gauges,
    )

    dp = Dataplane(DataplaneConfig(
        max_tables=2, max_rules=8, max_global_rules=8, max_ifaces=8,
        fib_slots=16, sess_slots=64, nat_mappings=2, nat_backends=4))
    coll = StatsCollector(dp)
    register_control_plane_metrics(coll.registry)
    # the KSR and kvserver families live on other processes/paths; fold
    # them into the same registry so cross-path duplicates are caught
    register_ksr_gauges(coll.registry, ReflectorRegistry(), path="/metrics")
    coll.registry.register("/kvstore", make_request_histogram())
    return coll.registry


def metrics_lint() -> list:
    """Build every registry the deployed processes serve and validate
    the registered families (MetricsRegistry.lint). Returns problems."""
    return _build_full_registry().lint()


def counters_lint() -> list:
    """Counter-parity pass: every StepStats field must map to a
    registered Prometheus family (stats/collector.py
    STEPSTATS_FAMILIES), and every registered ``vpp_tpu_pipeline_*``
    family must map back to a StepStats field — a pipeline counter
    added on either side without its observability twin fails here
    (and tier-1, via tests/test_exposition.py). The same discipline is
    enforced on the packed-aux rider (ISSUE 11 satellite): every
    PACKED_AUX_SCHEMA row past the fastpath trio must map through
    AUX_RIDER_STATS to a pump stats key that PUMP_STAT_GAUGES exports
    — widening the rider without its observability twin fails here."""
    registry = _build_full_registry()
    from vpp_tpu.pipeline.dataplane import PACKED_AUX_SCHEMA
    from vpp_tpu.pipeline.graph import StepStats
    from vpp_tpu.stats.collector import (
        AUX_RIDER_STATS,
        PUMP_STAT_GAUGES,
        STEPSTATS_FAMILIES,
    )

    problems = []
    # aux-rider parity (rows 0-2 are the fastpath trio consumed
    # positionally by io/pump.py _account_fastpath)
    if tuple(PACKED_AUX_SCHEMA[:3]) != ("fastpath", "rx", "sess_hits"):
        problems.append(
            "counters: PACKED_AUX_SCHEMA rows 0-2 must stay the "
            f"fastpath trio, got {PACKED_AUX_SCHEMA[:3]}")
    pump_keys = {stat_key for stat_key, _name, _h in PUMP_STAT_GAUGES}
    for row in PACKED_AUX_SCHEMA[3:]:
        key = AUX_RIDER_STATS.get(row)
        if key is None:
            problems.append(
                f"counters: aux rider row {row!r} has no pump-stats "
                f"mapping (stats/collector.py AUX_RIDER_STATS)")
        elif key not in pump_keys:
            problems.append(
                f"counters: aux rider row {row!r} maps to pump stat "
                f"{key!r} which PUMP_STAT_GAUGES does not export")
    for row in sorted(set(AUX_RIDER_STATS) - set(PACKED_AUX_SCHEMA)):
        problems.append(
            f"counters: AUX_RIDER_STATS maps {row!r} which is not a "
            f"PACKED_AUX_SCHEMA row (stale entry?)")
    fields = set(StepStats._fields)
    mapped = set(STEPSTATS_FAMILIES)
    for f in sorted(fields - mapped):
        problems.append(
            f"counters: StepStats.{f} has no Prometheus family mapping "
            f"(stats/collector.py STEPSTATS_FAMILIES)"
        )
    for f in sorted(mapped - fields):
        problems.append(
            f"counters: STEPSTATS_FAMILIES maps {f!r} which is not a "
            f"StepStats field (stale entry?)"
        )
    registered = {fam.name for _path, fam in registry.families()}
    for f, family in sorted(STEPSTATS_FAMILIES.items()):
        if family not in registered:
            problems.append(
                f"counters: StepStats.{f} maps to unregistered family "
                f"{family!r}"
            )
    mapped_families = set(STEPSTATS_FAMILIES.values())
    for name in sorted(registered):
        if name.startswith("vpp_tpu_pipeline_") and \
                name not in mapped_families:
            problems.append(
                f"counters: family {name!r} is in the pipeline "
                f"namespace but maps to no StepStats field"
            )
    # drop-cause parity (ISSUE 13): every pump drop-stats key must
    # export a reason label on vpp_tpu_pump_drops_total, and vice
    # versa — a drop cause added on either side without its twin is a
    # silent-loss regression waiting to happen
    from vpp_tpu.io.pump import PUMP_DROP_KEYS
    from vpp_tpu.stats.collector import PUMP_DROP_REASONS

    reason_keys = {k for k, _r in PUMP_DROP_REASONS}
    for k in sorted(set(PUMP_DROP_KEYS) - reason_keys):
        problems.append(
            f"counters: pump drop key {k!r} has no reason label on "
            f"vpp_tpu_pump_drops_total (stats/collector.py "
            f"PUMP_DROP_REASONS)")
    for k in sorted(reason_keys - set(PUMP_DROP_KEYS)):
        problems.append(
            f"counters: PUMP_DROP_REASONS maps {k!r} which is not an "
            f"io/pump.py PUMP_DROP_KEYS drop key (stale entry?)")
    # governor scalar parity (ISSUE 13): every control-loop snapshot
    # scalar the governor declares must export a registered gauge,
    # and every mapped gauge must exist in a live snapshot
    from vpp_tpu.io.governor import LatencyGovernor
    from vpp_tpu.stats.collector import GOVERNOR_STAT_GAUGES

    snap = LatencyGovernor(1000.0, slots=8, max_inflight=8).snapshot()
    mapped_keys = {k for k, _n, _h in GOVERNOR_STAT_GAUGES}
    for k in sorted(set(LatencyGovernor.SNAPSHOT_SCALARS) - mapped_keys):
        problems.append(
            f"counters: governor scalar {k!r} has no gauge mapping "
            f"(stats/collector.py GOVERNOR_STAT_GAUGES)")
    for k, name, _h in GOVERNOR_STAT_GAUGES:
        if k not in snap:
            problems.append(
                f"counters: GOVERNOR_STAT_GAUGES maps {k!r} which the "
                f"governor snapshot does not carry (stale entry?)")
        if name not in registered:
            problems.append(
                f"counters: governor scalar {k!r} maps to "
                f"unregistered family {name!r}")
    # fleet parity (ISSUE 18): the collector's drop-cause axis must be
    # exactly the causes the steering tier + fleet pump attribute (a
    # cause added on either side without its observability twin breaks
    # the conservation identity's visibility), and every
    # vpp_tpu_fleet_* family must come from the ONE declaration
    from vpp_tpu.fleet.steering import STEER_DROP_CAUSES
    from vpp_tpu.io.fleet import QUEUE_DROP_CAUSES
    from vpp_tpu.stats.collector import (
        FLEET_DROP_CAUSES,
        FLEET_GAUGE_FAMILIES,
    )

    attributed = tuple(STEER_DROP_CAUSES) + tuple(QUEUE_DROP_CAUSES)
    for c in sorted(set(attributed) - set(FLEET_DROP_CAUSES)):
        problems.append(
            f"counters: fleet drop cause {c!r} is attributed but has "
            f"no cause label on vpp_tpu_fleet_drops_total "
            f"(stats/collector.py FLEET_DROP_CAUSES)")
    for c in sorted(set(FLEET_DROP_CAUSES) - set(attributed)):
        problems.append(
            f"counters: FLEET_DROP_CAUSES lists {c!r} which neither "
            f"the steering tier nor the fleet pump attributes "
            f"(stale entry?)")
    declared = {name for name, _h, _k in FLEET_GAUGE_FAMILIES}
    for name in sorted(registered):
        if name.startswith("vpp_tpu_fleet_") and name not in declared:
            problems.append(
                f"counters: family {name!r} is in the fleet namespace "
                f"but not declared in FLEET_GAUGE_FAMILIES")
    for name in sorted(declared - registered):
        problems.append(
            f"counters: FLEET_GAUGE_FAMILIES declares {name!r} which "
            f"is not registered")
    return problems


def partitions_lint() -> list:
    """Partition-rule completeness pass (``--partitions``; ISSUE 12):
    every DataplaneTables field must resolve to an explicit rule in
    vpp_tpu/parallel/partition.py (sharded or replicated-by-design),
    and every rule must match at least one field (stale rules are
    findings). Pure import — no jax arrays touched. Run from tier-1
    via tests/test_partition.py. ISSUE 16 folds the Pallas-kernel pass
    in: every PALLAS_KERNELS entry must import, its table operands
    must resolve in the partition spec, and its knob must be REJECTED
    at config time on a rule-sharded mesh (never fail inside
    pallas_call)."""
    _repo_on_path()
    from vpp_tpu.parallel.partition import partition_lint

    return partition_lint() + _pallas_kernel_problems()


def _pallas_kernel_problems() -> list:
    """The Pallas side of the --partitions pass (ISSUE 16): walk
    tools/analysis/jit_manifest.py PALLAS_KERNELS and verify, per
    kernel, that (a) its jit entry and dispatch root import from the
    named module, (b) every DataplaneTables field its operands are
    built from resolves to an explicit partition rule, and (c) an
    explicit pallas knob on a rule-sharded mesh is rejected by
    validate_partitioning with an error naming PARTITION_RULES — a
    kernel whose operands would arrive sharded must be turned away at
    config time, not crash at trace time inside pallas_call."""
    _repo_on_path()
    import importlib

    from analysis.jit_manifest import JIT_SITES, PALLAS_KERNELS
    from vpp_tpu.parallel.partition import (
        PartitionError,
        spec_for,
        validate_partitioning,
    )
    from vpp_tpu.pipeline.tables import DataplaneConfig, DataplaneTables

    problems = []
    for (relpath, scope), entry in sorted(PALLAS_KERNELS.items()):
        name = f"{relpath}:{scope}"
        if (relpath, scope) not in JIT_SITES:
            problems.append(
                f"partitions: pallas kernel {name} is not a registered "
                "JIT_SITES entry (jit manifest desynced)")
        modname = relpath[:-3].replace("/", ".")
        try:
            mod = importlib.import_module(modname)
        except Exception as e:  # noqa: BLE001 - lint reports, not raises
            problems.append(
                f"partitions: pallas kernel {name} module import "
                f"failed: {e}")
            continue
        for attr in (scope.lstrip("@"), entry["fn"]):
            if not callable(getattr(mod, attr, None)):
                problems.append(
                    f"partitions: pallas kernel {name} names "
                    f"{attr!r} which {modname} does not define")
        for f in entry["fields"]:
            if f not in DataplaneTables._fields:
                problems.append(
                    f"partitions: pallas kernel {name} operand {f!r} "
                    "is not a DataplaneTables field (stale entry?)")
                continue
            try:
                spec_for(f)
            except PartitionError as e:
                problems.append(
                    f"partitions: pallas kernel {name} operand {f!r} "
                    f"has no partition rule: {e}")
    # mesh rejection: every pallas-selecting knob, on a 2-way
    # rule-sharded mesh, must raise at config time with an error that
    # points the operator at PARTITION_RULES
    base = dict(max_tables=2, max_rules=8, max_global_rules=8,
                max_ifaces=8, fib_slots=16, sess_slots=64,
                nat_mappings=2, nat_backends=4)
    knobs = sorted({e["knob"] for e in PALLAS_KERNELS.values()})
    for knob in knobs:
        cfg = DataplaneConfig(**base, **{knob: "pallas"})
        try:
            validate_partitioning(cfg, rule_shards=2)
        except ValueError as e:
            if "PARTITION_RULES" not in str(e):
                problems.append(
                    f"partitions: mesh rejection of {knob}='pallas' "
                    "does not name PARTITION_RULES (operator has no "
                    f"pointer to the fix): {e}")
        else:
            problems.append(
                f"partitions: {knob}='pallas' on a rule-sharded mesh "
                "was NOT rejected by validate_partitioning — the step "
                "would fail inside pallas_call at trace time")
        # the same knob on an unsharded mesh must pass (standalone
        # pallas is the supported deployment)
        try:
            validate_partitioning(cfg, rule_shards=1)
        except ValueError as e:
            problems.append(
                f"partitions: {knob}='pallas' rejected even without "
                f"rule sharding: {e}")
    return problems


def _bv_plane_problems(name: str, bv, nrules: int, max_rules: int) -> list:
    """Invariants of ONE compiled BvTable against its live rule count."""
    import numpy as np

    from vpp_tpu.ops.acl_bv import DIMS, bv_capacity

    problems = []
    cap_i, cap_w, cap_pr = bv_capacity(max_rules, True)
    planes = {dim: getattr(bv, f"bm_{dim}") for dim in DIMS}
    planes["proto"] = bv.bm_proto
    for k, dim in enumerate(DIMS):
        bnd = getattr(bv, f"bnd_{dim}")
        n = int(bv.nbnd[k])
        if len(bnd) != cap_i:
            problems.append(
                f"tables: {name}.{dim} boundary capacity {len(bnd)} != "
                f"bv_capacity {cap_i}")
        live = bnd[:n].astype(np.int64)
        if n and not (np.diff(live) > 0).all():
            problems.append(
                f"tables: {name}.{dim} boundaries not strictly sorted")
        if n and live[0] != 0:
            problems.append(
                f"tables: {name}.{dim} boundary[0] != 0 (value space "
                f"must be fully covered)")
    for pname, bm in planes.items():
        if bm.shape[-1] != cap_w or cap_w != max(1, (max_rules + 31) // 32):
            problems.append(
                f"tables: {name}.{pname} word width {bm.shape[-1]} does "
                f"not match padded rule capacity {max_rules}")
        # padding inert, rule axis: no bit of a row >= nrules anywhere
        for w in range(bm.shape[-1]):
            lo_rule = w * 32
            nbits = min(32, max(0, nrules - lo_rule))
            allowed = np.uint32((1 << nbits) - 1)
            if (bm[..., w] & ~allowed).any():
                problems.append(
                    f"tables: {name}.{pname} word {w} sets bits of "
                    f"padding rules (nrules={nrules})")
        # padding inert, interval axis: rows past the live boundary
        # count must be all-zero (a clipped lookup can never land
        # there; a stale bit would be a silent wrong-match hazard)
        if pname != "proto":
            n = int(bv.nbnd[list(DIMS).index(pname)])
            if bm[n:].any():
                problems.append(
                    f"tables: {name}.{pname} has bits set in interval "
                    f"rows >= nbnd ({n})")
    return problems


def _lpm_plane_problems() -> list:
    """LPM/ECMP structure invariants (ISSUE 15): stage a
    representative FIB (duplicate prefixes, /0 + /32 edges, an ECMP
    group) and validate the compiled per-length planes — strict sort
    within each plane's live prefix, pad inertness past the live
    count, count/cap consistency, and group membership (every way of
    a live group carries one of its registered members; unregistered
    rows are fully zeroed)."""
    _repo_on_path()
    import numpy as np

    from vpp_tpu.ops.lpm import LPM_LENGTHS, LPM_PAD, lpm_field
    from vpp_tpu.pipeline.tables import DataplaneConfig, TableBuilder
    from vpp_tpu.pipeline.vector import Disposition

    problems = []
    b = TableBuilder(DataplaneConfig(
        max_tables=2, max_rules=8, max_global_rules=8, max_ifaces=8,
        fib_slots=64, sess_slots=64, nat_mappings=2, nat_backends=4,
        fib_impl="lpm", fib_ecmp_groups=4, fib_ecmp_ways=4))
    b.add_route("0.0.0.0/0", 1, Disposition.REMOTE, node_id=1)
    b.add_route("255.255.255.255/32", 2, Disposition.LOCAL)
    b.add_route("10.1.1.0/24", 3, Disposition.LOCAL)
    b.add_route("10.1.2.0/24", 3, Disposition.LOCAL)
    b.add_route("10.1.1.0/24", 4, Disposition.LOCAL)   # duplicate
    b.set_nh_group(1, [(101, 5, -1), (102, 6, 2)])
    b.add_route("10.9.0.0/16", 5, Disposition.REMOTE, group=1)
    b.del_route("10.1.2.0/24")
    b._restage_lpm()
    if not b.lpm_ok():
        problems.append("tables: representative LPM table not lpm_ok")
    for length in range(LPM_LENGTHS):
        plane = b.lpm_planes[lpm_field(length)]
        n = int(b.lpm_cnt[length])
        cap = plane.shape[1]
        if n > cap:
            problems.append(
                f"tables: lpm /{length} count {n} exceeds cap {cap}")
            continue
        live = plane[0, :n].astype(np.int64)
        if n > 1 and not (np.diff(live) > 0).all():
            problems.append(
                f"tables: lpm /{length} prefixes not STRICTLY sorted "
                "(duplicates must dedupe to the lowest slot)")
        if (plane[0, n:] != LPM_PAD).any() or (plane[1, n:] != 0).any():
            problems.append(
                f"tables: lpm /{length} pad rows past count {n} are "
                "not inert")
        slots = plane[1, :n].astype(np.int64)
        if n and ((slots < 0) | (slots >= b.config.fib_slots)).any():
            problems.append(
                f"tables: lpm /{length} slot row out of range")
        elif n and (b.fib_plen[slots] != length).any():
            problems.append(
                f"tables: lpm /{length} slot row points at a route of "
                "another length")
    # stride hint tables: per length, monotone non-decreasing rows
    # bracketing [0, count] (a misordered hint silently corrupts the
    # bounded bisection)
    from vpp_tpu.ops.lpm import lpm_hint_layout

    layout, hint_rows = lpm_hint_layout(b.lpm_caps)
    if len(b.lpm_hint) != hint_rows:
        problems.append(
            f"tables: lpm hint rows {len(b.lpm_hint)} != layout "
            f"{hint_rows}")
    else:
        for length in range(LPM_LENGTHS):
            bb, off, _steps = layout[length]
            if off < 0:
                continue
            h = b.lpm_hint[off:off + (1 << bb) + 1]
            if (np.diff(h) < 0).any() or h[0] != 0 \
                    or h[-1] != int(b.lpm_cnt[length]):
                problems.append(
                    f"tables: lpm /{length} hint rows not a monotone "
                    "[0, count] bracket")
    # the duplicate 10.1.1.0/24 must resolve to the LOWER slot (the
    # dense argmax tie-break)
    p24 = b.lpm_planes[lpm_field(24)]
    n24 = int(b.lpm_cnt[24])
    dup = p24[1, :n24][p24[0, :n24] == (10 << 24 | 1 << 16 | 1 << 8)]
    if len(dup) != 1 or int(b.fib_tx_if[int(dup[0])]) != 3:
        problems.append(
            "tables: lpm duplicate-prefix dedupe does not keep the "
            "lowest slot")
    # group membership
    registered = set(b.nh_groups)
    grp_vals = set(int(g) for g in np.unique(b.fib_grp) if g >= 0)
    if not grp_vals <= registered:
        problems.append(
            f"tables: routes reference unregistered ECMP group(s) "
            f"{sorted(grp_vals - registered)}")
    for gid in range(b.fib_grp_nh.shape[0]):
        if gid in registered:
            members = set(tuple(m) for m in b.nh_groups[gid]["members"])
            rows = set(zip(b.fib_grp_nh[gid].tolist(),
                           b.fib_grp_tx_if[gid].tolist(),
                           b.fib_grp_node[gid].tolist()))
            if not rows <= members:
                problems.append(
                    f"tables: ecmp group {gid} ways carry non-member "
                    "entries")
            if int(b.fib_grp_n[gid]) != len(members):
                problems.append(
                    f"tables: ecmp group {gid} member count desynced")
        elif (int(b.fib_grp_n[gid]) != 0 or b.fib_grp_nh[gid].any()):
            problems.append(
                f"tables: unregistered ecmp group {gid} row not zeroed")
    return problems


def _svc_plane_problems() -> list:
    """Service-LB / overlay plane invariants (ISSUE 19): stage a
    representative service registry and validate the compiled svc_*
    arrays — VIP rows sorted by (ip, port, proto), padding rows inert
    (bk_n 0 and all-zero: a row must never serve before its whole
    backend set is staged), every way of a live row carrying a
    registered backend with way counts matching the weighted
    largest-remainder targets — then roll one backend and require the
    sticky fill to keep every surviving backend's ways. Also pins the
    tenancy-off VNI→tenant plane shape the overlay decap admission
    depends on (slot 0 = DEFAULT_VNI, everything else -1)."""
    _repo_on_path()
    import numpy as np

    from vpp_tpu.ops.vxlan import DEFAULT_VNI
    from vpp_tpu.pipeline.tables import DataplaneConfig, TableBuilder

    problems = []
    b = TableBuilder(DataplaneConfig(
        max_tables=2, max_rules=8, max_global_rules=8, max_ifaces=8,
        fib_slots=16, sess_slots=64, nat_mappings=2, nat_backends=4,
        svc_vips=4, svc_backend_ways=8))
    vip1, vip2 = 0x0A600001, 0x0A600002
    bk = [0x0A000001, 0x0A000002, 0x0A000003, 0x0A000004, 0x0A000005]
    b.set_service(vip2, 80, 6, [(bk[0], 8080, 1), (bk[1], 8080, 1)])
    b.set_service(vip1, 443, 6,
                  [(bk[2], 8443, 2), (bk[3], 8443, 1),
                   (bk[4], 8443, 1)], self_snat=True)
    svc = b.svc
    n = len(b.services)
    keys = list(zip(svc["svc_vip_ip"][:n].astype(np.int64).tolist(),
                    svc["svc_vip_port"][:n].tolist(),
                    svc["svc_vip_proto"][:n].tolist()))
    if keys != sorted(keys):
        problems.append(
            "tables: svc VIP rows not sorted by (ip, port, proto)")
    if (svc["svc_bk_n"][n:].any() or svc["svc_vip_ip"][n:].any()
            or svc["svc_bk_ip"][n:].any()
            or svc["svc_bk_port"][n:].any()):
        problems.append(
            "tables: svc padding rows past the live count are not "
            "inert (a padding row could serve)")
    order = sorted(b.services)
    for r, key in enumerate(order):
        e = b.services[key]
        members = set((m[0], m[1]) for m in e["members"])
        ways = set(zip(svc["svc_bk_ip"][r].astype(np.int64).tolist(),
                       svc["svc_bk_port"][r].tolist()))
        if not ways <= members:
            problems.append(
                f"tables: svc row {r} ways carry non-member backends")
        if int(svc["svc_bk_n"][r]) != len(e["members"]):
            problems.append(f"tables: svc row {r} bk_n desynced")
    # weighted largest-remainder fill: vip1's weight-2 backend owns
    # exactly half the ways (targets [4, 2, 2] over 8)
    r1 = order.index((vip1, 443, 6))
    row = svc["svc_bk_ip"][r1].astype(np.int64)
    if int((row == bk[2]).sum()) != 4:
        problems.append(
            "tables: svc weighted fill wrong — weight-2 backend "
            f"owns {int((row == bk[2]).sum())}/8 ways, expected 4")
    # sticky replacement: roll vip2's second backend; the survivor
    # must keep every way it owned (flows it serves never remap)
    r2 = order.index((vip2, 80, 6))
    before = svc["svc_bk_ip"][r2].astype(np.int64).copy()
    b.set_service(vip2, 80, 6, [(bk[0], 8080, 1), (0x0A000009, 8080, 1)])
    after = b.svc["svc_bk_ip"][r2].astype(np.int64)
    survivor = before == bk[0]
    if not (after[survivor] == bk[0]).all():
        problems.append(
            "tables: svc sticky fill moved a surviving backend's ways")
    if not (after[~survivor] == 0x0A000009).all():
        problems.append(
            "tables: svc replaced backend's ways not handed to the "
            "replacement")
    # overlay admission plane (tenancy off): exactly DEFAULT_VNI maps
    # (to tenant 0); any other VNI must fail closed at decap
    if int(b.tnt["tnt_vni"][0]) != DEFAULT_VNI:
        problems.append(
            "tables: tenancy-off tnt_vni[0] is not DEFAULT_VNI — the "
            "single-tenant overlay would admit nothing")
    if (b.tnt["tnt_vni"][1:] != -1).any():
        problems.append(
            "tables: unconfigured tnt_vni slots are not -1 (stray "
            "VNIs would be admitted)")
    return problems


def tables_lint() -> list:
    """Table-structure invariant pass (`--tables`): commit a
    representative rule set through a BV-enabled TableBuilder and
    validate the compiled structure + the cross-implementation
    capacity constants. Returns problems."""
    _repo_on_path()
    import ipaddress

    from vpp_tpu.ir.rule import Action, ContivRule, Protocol
    from vpp_tpu.ops.acl_bv import bv_capacity, bv_global_bytes
    from vpp_tpu.ops.acl_mxu import mxu_rule_capacity
    from vpp_tpu.pipeline.tables import DataplaneConfig, TableBuilder

    cfg = DataplaneConfig(
        max_tables=2, max_rules=16, max_global_rules=96, max_ifaces=8,
        fib_slots=16, sess_slots=64, nat_mappings=2, nat_backends=4,
        classifier="bv")
    b = TableBuilder(cfg)
    rules = [
        ContivRule(action=Action.PERMIT, protocol=Protocol.TCP,
                   src_network=ipaddress.ip_network(f"10.{i}.0.0/16"),
                   dest_port=80 + i)
        for i in range(40)
    ] + [
        ContivRule(action=Action.DENY, protocol=Protocol.UDP,
                   dest_port=0),
        ContivRule(action=Action.PERMIT),        # wildcard everything
        ContivRule(action=Action.DENY, protocol=Protocol.TCP,
                   dest_port=65535),
        ContivRule(action=Action.DENY),          # terminal deny-all
    ]
    b.set_global_table(rules)
    b.set_local_table(0, rules[:7])
    # slot 1 stays empty: its planes must be entirely inert

    problems = _bv_plane_problems("glb", b.glb_bv, b.glb_nrules,
                                  cfg.max_global_rules)
    for slot, nrules in ((0, 7), (1, 0)):
        from vpp_tpu.ops.acl_bv import BvTable

        local = BvTable(
            bnd_src=b.acl_bv["bnd_src"][slot],
            bnd_dst=b.acl_bv["bnd_dst"][slot],
            bnd_sport=b.acl_bv["bnd_sport"][slot],
            bnd_dport=b.acl_bv["bnd_dport"][slot],
            nbnd=b.acl_bv["nbnd"][slot],
            bm_src=b.acl_bv["src"][slot], bm_dst=b.acl_bv["dst"][slot],
            bm_sport=b.acl_bv["sport"][slot],
            bm_dport=b.acl_bv["dport"][slot],
            bm_proto=b.acl_bv["proto"][slot],
            ok=bool(b.acl_bv_ok[slot]), build_ms=0.0,
        )
        problems += _bv_plane_problems(f"local[{slot}]", local, nrules,
                                       cfg.max_rules)
    problems += _lpm_plane_problems()
    problems += _svc_plane_problems()
    # cross-implementation capacity constants
    for r in (cfg.max_rules, cfg.max_global_rules, 1024, 10240):
        ib, w, _pr = bv_capacity(r, True)
        if ib != 2 * r + 2:
            problems.append(
                f"tables: bv interval capacity {ib} != 2*{r}+2")
        if w * 32 < r:
            problems.append(
                f"tables: bv word capacity {w}*32 < {r} rules")
        if mxu_rule_capacity(r) < r:
            problems.append(
                f"tables: mxu rule capacity {mxu_rule_capacity(r)} < {r}")
        if bv_global_bytes(r) < ib * w * 4 * 4:
            problems.append(
                f"tables: bv_global_bytes({r}) smaller than its own "
                f"bitmap matrices")
    return problems
