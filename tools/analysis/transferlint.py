"""The ``--transfers`` device-boundary fetch pass (ISSUE 20).

The regression class fixed by hand in PRs 6, 8 and 12: table-scale
device values materialized on host (``np.asarray``, ``jax.device_get``,
``.item()`` / ``int()``) on the control path — "~270 MB crosses the
transport".  The pass taints every value reachable from a
`DataplaneTables` pytree (``X.tables`` attribute loads, parameters named
``tables`` or annotated ``DataplaneTables``, the persistent pump's
table-carry slots) and flags host-materialization sinks on tainted
values, unless the enclosing function is an approved fetch site in
`transfer_manifest.TRANSFER_SITES` (snapshot drains, bench captures,
the packed-result fetch).

Taint propagates through names, attribute/subscript access, tuple
packing, arithmetic, and device-side calls (``jnp.*`` / ``jax.lax.*``);
it does NOT survive host metadata access (``.shape`` / ``.dtype`` /
``.ndim`` / ``.size`` / ``.nbytes``) — shapes live on host already.
Closures inherit the enclosing scope's taint (the pump's fetch workers).

Rules (docs/STATIC_ANALYSIS.md catalog):

* ``transfer-host-fetch`` — host materialization of a tables-reachable
  device value outside an approved site.  Suppress one line with
  ``# transfer-ok: <reason>``; add a site to the manifest when the whole
  function IS a sanctioned drain (docs/STATIC_ANALYSIS.md "how to add an
  approved transfer site").
* ``transfer-site-stale`` — a TRANSFER_SITES entry that no longer
  resolves to a scanned function (file gone, function renamed): drop or
  fix it, dead allowlist entries hide future regressions.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, List, Set, Tuple

from analysis.common import Finding, iter_source_files, parse_suppressions

TRANSFER_ROOTS = ("vpp_tpu", "bench.py")

# attribute names that hold a DataplaneTables pytree
TAINT_ATTRS = {"tables", "_tables0", "_tables_pending", "_tables_final"}
# parameter names that carry one
TAINT_PARAMS = {"tables", "tbl", "tables0"}
# host-metadata access does not move array bytes
HOST_ATTRS = {"shape", "dtype", "ndim", "size", "nbytes", "sharding"}
# numpy module aliases and materializing constructors
NP_NAMES = {"np", "numpy", "_np"}
NP_SINKS = {"asarray", "array", "ascontiguousarray"}
# device-side module aliases: calls through these keep values on device
DEVICE_MODS = {"jnp", "jax", "lax"}


def _qual(stack: List[str]) -> str:
    return ".".join(stack) if stack else "<module>"


class TransferPass:
    def __init__(self, repo: Path, roots=TRANSFER_ROOTS, manifest=None):
        self.repo = repo
        self.roots = roots
        if manifest is None:
            from analysis import transfer_manifest as manifest
        self.sites: Dict[Tuple[str, str], str] = dict(
            manifest.TRANSFER_SITES)
        self.findings: List[Finding] = []
        self._seen_scopes: Set[Tuple[str, str]] = set()

    def run(self) -> List[Finding]:
        scanned_files = set()
        for relpath, path in iter_source_files(self.repo, self.roots):
            scanned_files.add(relpath)
            src = path.read_text()
            try:
                tree = ast.parse(src, filename=relpath)
            except SyntaxError:
                continue
            sup = parse_suppressions(src, relpath)
            self.findings.extend(sup.problems)
            self._scan_scope(relpath, tree.body, [], set(), sup)
        for (relpath, qualname), _reason in sorted(self.sites.items()):
            if relpath not in scanned_files:
                self.findings.append(Finding(
                    relpath, 1, "transfer-site-stale",
                    f"TRANSFER_SITES entry ({relpath!r}, {qualname!r}) "
                    f"names a file outside the scanned tree"))
            elif qualname != "*" and \
                    (relpath, qualname) not in self._seen_scopes:
                self.findings.append(Finding(
                    relpath, 1, "transfer-site-stale",
                    f"TRANSFER_SITES entry {qualname!r} does not "
                    f"resolve to a function in {relpath}: drop or fix "
                    f"it (dead allowlist entries hide regressions)"))
        return self.findings

    # ------------------------------------------------------------------
    def _allowed(self, relpath: str, stack: List[str]) -> bool:
        if (relpath, "*") in self.sites:
            return True
        # an inner closure is covered by its enclosing approved site
        for i in range(len(stack), 0, -1):
            if (relpath, ".".join(stack[:i])) in self.sites:
                return True
        return False

    def _scan_scope(self, relpath, body, stack, inherited, sup) -> None:
        """One lexical scope: collect tainted names, then find sinks.
        Nested functions recurse with the outer taint inherited."""
        self._seen_scopes.add((relpath, _qual(stack)))
        tainted: Set[str] = set(inherited)
        nested = []

        def is_tainted(expr) -> bool:
            if isinstance(expr, ast.Name):
                return expr.id in tainted
            if isinstance(expr, ast.Attribute):
                if expr.attr in HOST_ATTRS:
                    return False
                if expr.attr in TAINT_ATTRS:
                    return True
                return is_tainted(expr.value)
            if isinstance(expr, ast.Subscript):
                return is_tainted(expr.value)
            if isinstance(expr, ast.Starred):
                return is_tainted(expr.value)
            if isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
                return any(is_tainted(e) for e in expr.elts)
            if isinstance(expr, ast.BinOp):
                return is_tainted(expr.left) or is_tainted(expr.right)
            if isinstance(expr, ast.UnaryOp):
                return is_tainted(expr.operand)
            if isinstance(expr, ast.Compare):
                return is_tainted(expr.left) or \
                    any(is_tainted(c) for c in expr.comparators)
            if isinstance(expr, ast.IfExp):
                return is_tainted(expr.body) or is_tainted(expr.orelse)
            if isinstance(expr, ast.NamedExpr):
                return is_tainted(expr.value)
            if isinstance(expr, ast.Call):
                f = expr.func
                # getattr(tables, name) reaches a column
                if isinstance(f, ast.Name) and f.id == "getattr" and \
                        expr.args and is_tainted(expr.args[0]):
                    return True
                # device-side transforms keep the value on device:
                # jnp.sum(tables.x), jax.lax.*, tainted.method(...)
                if isinstance(f, ast.Attribute):
                    root = f.value
                    while isinstance(root, ast.Attribute):
                        root = root.value
                    if isinstance(root, ast.Name) and \
                            root.id in DEVICE_MODS:
                        # device_get is the sink itself: its RESULT is
                        # a host array, not a tainted device value
                        if f.attr == "device_get":
                            return False
                        return any(is_tainted(a) for a in expr.args)
                    if f.attr not in ("item",) and is_tainted(f.value):
                        # tainted.astype(...)/.sum()/.reshape(...):
                        # still a device value
                        return True
                return False
            return False

        def seed_args(fn) -> Set[str]:
            out = set()
            args = fn.args
            for a in (args.posonlyargs + args.args + args.kwonlyargs +
                      [args.vararg, args.kwarg]):
                if a is None:
                    continue
                ann = a.annotation
                ann_name = ""
                if isinstance(ann, ast.Name):
                    ann_name = ann.id
                elif isinstance(ann, ast.Attribute):
                    ann_name = ann.attr
                elif isinstance(ann, ast.Constant) and \
                        isinstance(ann.value, str):
                    ann_name = ann.value.split(".")[-1]
                if a.arg in TAINT_PARAMS or \
                        ann_name == "DataplaneTables":
                    out.add(a.arg)
            return out

        # --- taint fixpoint over assignments in this scope ------------
        def collect(stmts):
            for s in stmts:
                if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                if isinstance(s, ast.Assign):
                    if is_tainted(s.value):
                        for t in s.targets:
                            _taint_target(t)
                    elif isinstance(s.value, ast.Tuple) and len(
                            s.targets) == 1 and isinstance(
                            s.targets[0], ast.Tuple) and len(
                            s.targets[0].elts) == len(s.value.elts):
                        for t, v in zip(s.targets[0].elts, s.value.elts):
                            if is_tainted(v):
                                _taint_target(t)
                elif isinstance(s, (ast.AnnAssign, ast.AugAssign)):
                    if s.value is not None and is_tainted(s.value):
                        _taint_target(s.target)
                elif isinstance(s, ast.For):
                    if is_tainted(s.iter):
                        _taint_target(s.target)
                    collect(s.body + s.orelse)
                elif isinstance(s, ast.With):
                    for item in s.items:
                        if item.optional_vars is not None and \
                                is_tainted(item.context_expr):
                            _taint_target(item.optional_vars)
                    collect(s.body)
                elif isinstance(s, (ast.If,)):
                    collect(s.body + s.orelse)
                elif isinstance(s, ast.While):
                    collect(s.body + s.orelse)
                elif isinstance(s, ast.Try):
                    collect(s.body + s.orelse + s.finalbody)
                    for h in s.handlers:
                        collect(h.body)

        def _taint_target(t):
            if isinstance(t, ast.Name):
                tainted.add(t.id)
            elif isinstance(t, (ast.Tuple, ast.List)):
                for e in t.elts:
                    _taint_target(e)
            elif isinstance(t, ast.Starred):
                _taint_target(t.value)

        for _ in range(3):
            before = len(tainted)
            collect(body)
            if len(tainted) == before:
                break

        # --- sink detection -------------------------------------------
        allowed = self._allowed(relpath, stack)

        def visit(node):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                nested.append(node)
                return
            if isinstance(node, ast.ClassDef):
                return  # methods scanned as Class.method scopes below
            if isinstance(node, ast.Call):
                self._check_sink(relpath, stack, node, is_tainted,
                                 allowed, sup)
            for child in ast.iter_child_nodes(node):
                visit(child)

        for s in body:
            visit(s)

        for fn in nested:
            inner = set(tainted) | seed_args(fn)
            self._scan_scope(relpath, fn.body, stack + [fn.name],
                             inner, sup)

        # class bodies: methods are scopes named Class.method
        for s in body:
            if isinstance(s, ast.ClassDef):
                for m in s.body:
                    if isinstance(m, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                        self._scan_scope(
                            relpath, m.body, stack + [s.name, m.name],
                            seed_args(m), sup)

    def _check_sink(self, relpath, stack, call, is_tainted, allowed,
                    sup) -> None:
        f = call.func
        sink = None
        if isinstance(f, ast.Name) and f.id in ("int", "float", "bool"):
            if call.args and is_tainted(call.args[0]):
                sink = f"{f.id}() on a device value"
        elif isinstance(f, ast.Attribute):
            base = f.value
            if isinstance(base, ast.Name) and base.id in NP_NAMES and \
                    f.attr in NP_SINKS:
                if any(is_tainted(a) for a in call.args):
                    sink = f"np.{f.attr}() host materialization"
            elif f.attr == "device_get":
                if any(is_tainted(a) for a in call.args):
                    sink = "jax.device_get() host materialization"
            elif f.attr == "item" and not call.args and \
                    is_tainted(base):
                sink = ".item() device sync"
        if sink is None:
            return
        if allowed:
            return
        if call.lineno in sup.transfer:
            return
        self.findings.append(Finding(
            relpath, call.lineno, "transfer-host-fetch",
            f"{sink} of a DataplaneTables-reachable value in "
            f"{_qual(stack)}(): table-scale device->host fetch outside "
            f"the approved sites (tools/analysis/transfer_manifest.py)"))


def transfers_lint(repo=None, roots=TRANSFER_ROOTS,
                   manifest=None) -> List[Finding]:
    """Run the pass; returns unsuppressed findings (empty == clean)."""
    if repo is None:
        repo = Path(__file__).resolve().parents[2]
    return TransferPass(Path(repo), roots, manifest).run()
