"""The ``--jax`` tracer/recompile hygiene pass.

Scope: vpp_tpu/ops, vpp_tpu/pipeline, vpp_tpu/parallel — the code that
is traced into XLA programs. Rules (docs/STATIC_ANALYSIS.md catalog):

* ``jit-unregistered``   — a ``jax.jit`` call site not enumerated in
  tools/analysis/jit_manifest.py. Every jit is a compile-cache entry
  with a recompile blast radius; new ones land with a manifest reason.
* ``jit-manifest-stale`` — a manifest entry (site or traced root) that
  no longer matches the tree.
* ``per-instance-jit``   — ``jax.jit`` of a closure that captures
  ``self`` inside a method: a fresh function identity per instance, so
  every instance re-traces (the PR-4 bug class: a fresh-closure-per-
  dataplane step factory silently recompiled per test and blew the
  tier-1 budget 3x).
* ``host-sync``          — ``.item()``, ``int()/float()/bool()`` of a
  tracer-derived value, or ``np.asarray/np.array`` of a device value
  inside traced code: forces a device round trip per call (or a
  ConcretizationTypeError at trace time).
* ``tracer-branch``      — Python ``if``/``while`` on a tracer-derived
  value inside traced code: per-value recompile or trace error; use
  ``lax.cond``/``jnp.where``.
* ``float-literal-dtype``— float literals fed to jnp constructors with
  no explicit dtype, and any ``float64`` reference: under x64 these
  silently drift the whole program to f64.
* ``lru-cache-method``   — ``lru_cache`` on a method: keys on ``self``,
  pinning instances live and giving per-instance cache behavior.
* ``unhashable-arg``     — list/dict/set literal passed to an
  ``lru_cache``'d factory: TypeError at call time.

Traced code = the reachability closure from the manifest's jitted entry
points: resolvable ``jax.jit(f)`` targets, decorated defs, plus the
manifest's TRACED_ROOTS for indirect wrappings. Host callbacks
(``io_callback``/``pure_callback`` first argument) are excluded — they
run on the host by construction.

Suppression: ``# jax-ok: <reason>`` on the flagged line.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, List, Optional, Tuple

from analysis.common import Finding, iter_source_files, parse_suppressions

JAX_ROOTS = ("vpp_tpu/ops", "vpp_tpu/pipeline", "vpp_tpu/parallel",
             "vpp_tpu/tenancy")

ARRAY_MODULES = {"jnp", "lax", "jsp", "pl"}
STATIC_ATTRS = {"shape", "dtype", "ndim", "size", "sharding"}
CALLBACK_FUNCS = {"io_callback", "pure_callback", "debug_callback",
                  "callback"}
NP_SYNC_FUNCS = {"asarray", "array", "copy", "ascontiguousarray"}
JNP_FLOAT_CTORS = {"array", "asarray", "full", "arange"}
LRU_NAMES = {"lru_cache", "cache"}


class ModuleIndex:
    """Per-module AST index: defs by qualname, lexical child-def maps,
    and the import environment (vpp_tpu-internal bindings only)."""

    def __init__(self, repo: Path, relpath: str, tree: ast.Module,
                 sup) -> None:
        self.relpath = relpath
        self.tree = tree
        self.sup = sup
        self.defs: Dict[str, ast.AST] = {}
        # id(scope node) -> {name: def node}; key 0 == module scope
        self.children: Dict[int, Dict[str, ast.AST]] = {0: {}}
        self.obj_imports: Dict[str, Tuple[str, str]] = {}
        self.mod_imports: Dict[str, str] = {}
        self._index(tree, prefix="", scope_key=0)
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) and node.module and \
                    node.module.startswith("vpp_tpu"):
                base = node.module.replace(".", "/")
                for a in node.names:
                    if a.name == "*":
                        continue
                    if (repo / base / (a.name + ".py")).is_file():
                        self.mod_imports[a.asname or a.name] = \
                            f"{base}/{a.name}.py"
                    elif (repo / (base + ".py")).is_file():
                        self.obj_imports[a.asname or a.name] = \
                            (base + ".py", a.name)
            elif isinstance(node, ast.Import):
                for a in node.names:
                    if a.name.startswith("vpp_tpu") and \
                            (repo / (a.name.replace(".", "/") + ".py")
                             ).is_file():
                        bound = a.asname or a.name.split(".")[0]
                        if a.asname:
                            self.mod_imports[bound] = \
                                a.name.replace(".", "/") + ".py"

    def _index(self, node, prefix: str, scope_key: int) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{child.name}"
                self.defs[qual] = child
                self.children.setdefault(scope_key, {})[child.name] = child
                self.children.setdefault(id(child), {})
                self._index(child, f"{qual}.", id(child))
            elif isinstance(child, ast.ClassDef):
                # class bodies don't form a name-resolution scope for
                # methods; qualnames still carry the class for display
                self._index(child, f"{prefix}{child.name}.", scope_key)
            else:
                self._index(child, prefix, scope_key)


def _base_name(expr) -> Optional[str]:
    while isinstance(expr, ast.Attribute):
        expr = expr.value
    return expr.id if isinstance(expr, ast.Name) else None


def _is_jax_jit(expr) -> bool:
    return (isinstance(expr, ast.Attribute) and expr.attr == "jit"
            and _base_name(expr) == "jax") or \
           (isinstance(expr, ast.Name) and expr.id == "jit")


def _is_shard_map(expr) -> bool:
    return isinstance(expr, ast.Attribute) and expr.attr == "shard_map"


def _is_lru_decorator(dec) -> bool:
    if isinstance(dec, ast.Call):
        dec = dec.func
    return (isinstance(dec, ast.Name) and dec.id in LRU_NAMES) or \
           (isinstance(dec, ast.Attribute) and dec.attr in LRU_NAMES)


def _jit_decorator(dec) -> bool:
    """``@jax.jit`` / ``@jit`` / ``@functools.partial(jax.jit, ...)``."""
    if _is_jax_jit(dec):
        return True
    if isinstance(dec, ast.Call):
        f = dec.func
        if _is_jax_jit(f):
            return True
        if ((isinstance(f, ast.Attribute) and f.attr == "partial")
                or (isinstance(f, ast.Name) and f.id == "partial")):
            return bool(dec.args) and _is_jax_jit(dec.args[0])
    return False


class _Region:
    """One traced region: a def (or lambda) plus its lexical extent."""

    def __init__(self, module: ModuleIndex, node, qual: str,
                 scope_chain: List[ast.AST]):
        self.module = module
        self.node = node
        self.qual = qual
        # innermost-first enclosing def nodes, for name resolution
        self.scope_chain = scope_chain


class JaxPass:
    def __init__(self, repo: Path, roots=JAX_ROOTS, jit_sites=None,
                 traced_roots=None):
        if jit_sites is None or traced_roots is None:
            from analysis import jit_manifest

            jit_sites = jit_manifest.JIT_SITES if jit_sites is None \
                else jit_sites
            traced_roots = jit_manifest.TRACED_ROOTS if traced_roots is None \
                else traced_roots
        self.repo = repo
        self.roots = roots
        self.jit_sites = dict(jit_sites)
        self.traced_roots = set(traced_roots)
        self.findings: List[Finding] = []
        self.modules: Dict[str, ModuleIndex] = {}

    # --- top level ---
    def run(self) -> List[Finding]:
        for relpath, path in iter_source_files(self.repo, self.roots):
            src = path.read_text()
            try:
                tree = ast.parse(src, filename=relpath)
            except SyntaxError:
                continue  # the style pass reports parse failures
            sup = parse_suppressions(src, relpath)
            self.findings.extend(sup.problems)
            self.modules[relpath] = ModuleIndex(self.repo, relpath,
                                               tree, sup)
        seen_sites = set()
        regions: List[_Region] = []
        for mod in self.modules.values():
            regions.extend(self._collect_sites(mod, seen_sites))
        for relpath, qual in sorted(self.traced_roots):
            mod = self.modules.get(relpath)
            node = mod.defs.get(qual) if mod else None
            if node is None:
                self._emit(relpath, 1, "jit-manifest-stale",
                           f"traced root {qual!r} not found in {relpath}",
                           mod)
                continue
            regions.append(_Region(mod, node, qual,
                                   self._scope_chain(mod, node)))
        for key, reason in sorted(self.jit_sites.items()):
            if key not in seen_sites:
                self._emit(key[0], 1, "jit-manifest-stale",
                           f"manifest site {key[1]!r} has no matching "
                           f"jax.jit call ({reason})",
                           self.modules.get(key[0]))
        self._close_and_check(regions)
        self._module_rules()
        return self.findings

    def _emit(self, relpath: str, line: int, rule: str, msg: str,
              mod: Optional[ModuleIndex]) -> None:
        if mod is not None and line in mod.sup.jax:
            return
        self.findings.append(Finding(relpath, line, rule, msg))

    # --- name resolution ---
    def _scope_chain(self, mod: ModuleIndex, node) -> List[ast.AST]:
        """Enclosing def nodes of ``node``, innermost first."""
        chain: List[ast.AST] = []

        def descend(parent, stack):
            for child in ast.iter_child_nodes(parent):
                if child is node:
                    chain.extend(reversed(stack))
                    return True
                nstack = stack + [child] if isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)
                ) else stack
                if descend(child, nstack):
                    return True
            return False

        descend(mod.tree, [])
        return chain

    def _resolve(self, mod: ModuleIndex, scope_chain, name: str):
        """Resolve ``name`` to (module, qual, def node) or None."""
        for scope in scope_chain:
            hit = mod.children.get(id(scope), {}).get(name)
            if hit is not None:
                return mod, self._qual_of(mod, hit), hit
        hit = mod.children[0].get(name)
        if hit is not None:
            return mod, self._qual_of(mod, hit), hit
        target = mod.obj_imports.get(name)
        if target is not None:
            tmod = self.modules.get(target[0])
            if tmod is not None:
                hit = tmod.children[0].get(target[1])
                if hit is not None:
                    return tmod, self._qual_of(tmod, hit), hit
        return None

    def _qual_of(self, mod: ModuleIndex, node) -> str:
        for qual, d in mod.defs.items():
            if d is node:
                return qual
        return getattr(node, "name", "<lambda>")

    # --- jit call sites ---
    def _collect_sites(self, mod: ModuleIndex, seen) -> List[_Region]:
        regions: List[_Region] = []
        decorator_calls = set()
        for qual, d in mod.defs.items():
            for dec in getattr(d, "decorator_list", []):
                if _jit_decorator(dec):
                    decorator_calls.update(id(n) for n in ast.walk(dec))
                    key = (mod.relpath, f"@{qual}")
                    seen.add(key)
                    if key not in self.jit_sites:
                        self._emit(mod.relpath, d.lineno, "jit-unregistered",
                                   f"jit decorator on {qual!r} is not in "
                                   f"the jit manifest "
                                   f"(tools/analysis/jit_manifest.py)", mod)
                    regions.append(_Region(
                        mod, d, qual, self._scope_chain(mod, d)))

        def scan(parent, fstack):
            for child in ast.iter_child_nodes(parent):
                nstack = fstack
                if isinstance(child,
                              (ast.FunctionDef, ast.AsyncFunctionDef)):
                    nstack = fstack + [child]
                if isinstance(child, ast.Call) and _is_jax_jit(child.func) \
                        and id(child) not in decorator_calls:
                    self._one_site(mod, child, fstack, seen, regions)
                scan(child, nstack)

        scan(mod.tree, [])
        return regions

    def _one_site(self, mod, call, fstack, seen, regions) -> None:
        scope = self._qual_of(mod, fstack[-1]) if fstack else "<module>"
        key = (mod.relpath, scope)
        seen.add(key)
        if key not in self.jit_sites:
            self._emit(mod.relpath, call.lineno, "jit-unregistered",
                       f"jax.jit call in {scope!r} is not in the jit "
                       f"manifest (tools/analysis/jit_manifest.py)", mod)
        if not call.args:
            return
        target = call.args[0]
        if isinstance(target, ast.Call) and _is_shard_map(target.func) \
                and target.args:
            target = target.args[0]
        chain = list(reversed(fstack))
        if isinstance(target, ast.Lambda):
            regions.append(_Region(mod, target, f"{scope}.<lambda>",
                                   chain))
            if fstack and self._mentions_self(target):
                self._emit(mod.relpath, call.lineno, "per-instance-jit",
                           f"jax.jit of a self-capturing lambda in "
                           f"{scope!r}: fresh function identity per "
                           f"instance, re-traced per instance", mod)
            return
        if isinstance(target, ast.Name):
            hit = self._resolve(mod, chain, target.id)
            if hit is not None:
                tmod, tqual, tnode = hit
                regions.append(_Region(tmod, tnode, tqual,
                                       self._scope_chain(tmod, tnode)))
                # a LOCAL def jitted inside a method that closes over
                # self is the PR-4 recompile class
                if fstack and tmod is mod and tnode in ast.walk(fstack[-1]) \
                        and self._mentions_self(tnode):
                    self._emit(
                        mod.relpath, call.lineno, "per-instance-jit",
                        f"jax.jit of local closure {tqual!r} capturing "
                        f"self: fresh function identity per instance, "
                        f"re-traced per instance", mod)

    @staticmethod
    def _mentions_self(node) -> bool:
        return any(isinstance(n, ast.Name) and n.id == "self"
                   for n in ast.walk(node))

    # --- reachability closure + traced-region rules ---
    def _close_and_check(self, regions: List[_Region]) -> None:
        traced: Dict[int, _Region] = {}
        work = list(regions)
        while work:
            r = work.pop()
            if id(r.node) in traced:
                continue
            traced[id(r.node)] = r
            for name, line in self._region_refs(r):
                hit = self._resolve(r.module, [r.node] + r.scope_chain,
                                    name)
                if hit is not None and id(hit[2]) not in traced:
                    tmod, tqual, tnode = hit
                    work.append(_Region(
                        tmod, tnode, tqual,
                        self._scope_chain(tmod, tnode)))
        for r in traced.values():
            # skip regions lexically inside another traced region: the
            # enclosing region's checker covers them exactly once
            if any(id(s) in traced for s in r.scope_chain):
                continue
            _TaintChecker(self, r).run()

    def _region_refs(self, r: _Region):
        """(name, line) of every Name referenced in the region, host
        callback functions excluded."""
        skip = set()
        for node in ast.walk(r.node):
            if isinstance(node, ast.Call):
                f = node.func
                fname = f.attr if isinstance(f, ast.Attribute) else \
                    (f.id if isinstance(f, ast.Name) else None)
                if fname in CALLBACK_FUNCS and node.args:
                    skip.update(id(n) for n in ast.walk(node.args[0]))
        for node in ast.walk(r.node):
            if isinstance(node, ast.Name) and id(node) not in skip:
                yield node.id, node.lineno

    # --- module-wide rules (float64 refs, lru_cache hygiene) ---
    def _module_rules(self) -> None:
        for mod in self.modules.values():
            lru_defs = {}
            for qual, d in mod.defs.items():
                if any(_is_lru_decorator(dec)
                       for dec in getattr(d, "decorator_list", [])):
                    lru_defs[d.name] = qual
                    args = d.args.posonlyargs + d.args.args
                    if args and args[0].arg in ("self", "cls"):
                        self._emit(
                            mod.relpath, d.lineno, "lru-cache-method",
                            f"lru_cache on method {qual!r}: cache keys "
                            f"on the instance (leaks it, and behaves "
                            f"per-instance — memoize at module scope)",
                            mod)
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Attribute) and \
                        node.attr == "float64":
                    self._emit(mod.relpath, node.lineno,
                               "float-literal-dtype",
                               "float64 reference in traced-root code: "
                               "x64 drift doubles every downstream "
                               "buffer", mod)
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Name) and \
                        node.func.id in lru_defs:
                    for a in node.args:
                        if isinstance(a, (ast.List, ast.Dict, ast.Set)):
                            self._emit(
                                mod.relpath, node.lineno, "unhashable-arg",
                                f"unhashable literal passed to "
                                f"lru_cache'd {lru_defs[node.func.id]!r}",
                                mod)


class _TaintChecker:
    """Forward taint walk over one traced region: parameters and
    jnp/lax-derived values are tracers; host syncs and Python control
    flow on them are findings."""

    def __init__(self, owner: JaxPass, region: _Region):
        self.owner = owner
        self.r = region
        self.tainted: set = set()
        for node in ast.walk(region.node):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                a = node.args
                for arg in (a.posonlyargs + a.args + a.kwonlyargs
                            + ([a.vararg] if a.vararg else [])
                            + ([a.kwarg] if a.kwarg else [])):
                    self.tainted.add(arg.arg)

    def _emit(self, line: int, rule: str, msg: str) -> None:
        self.owner._emit(self.r.module.relpath, line, rule, msg,
                         self.r.module)

    def is_tainted(self, expr) -> bool:
        if expr is None or isinstance(expr, ast.Constant):
            return False
        if isinstance(expr, ast.Name):
            return expr.id in self.tainted
        if isinstance(expr, ast.Attribute):
            if expr.attr in STATIC_ATTRS:
                return False
            return self.is_tainted(expr.value)
        if isinstance(expr, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in expr.ops):
            # `x is None` is resolved at TRACE time (a tracer is never
            # None): static, whatever x is
            return False
        if isinstance(expr, ast.Call):
            f = expr.func
            if isinstance(f, ast.Name):
                if f.id == "len":
                    return False
                if f.id in ("int", "float", "bool"):
                    return False  # host value (flagged separately)
            if isinstance(f, ast.Attribute) and \
                    _base_name(f) in ARRAY_MODULES:
                return True
            return any(self.is_tainted(a) for a in expr.args) or \
                any(self.is_tainted(kw.value) for kw in expr.keywords) or \
                self.is_tainted(f)
        return any(self.is_tainted(c) for c in ast.iter_child_nodes(expr))

    def run(self) -> None:
        self._walk(self.r.node)

    def _walk(self, node) -> None:
        for stmt in ast.iter_child_nodes(node):
            self._stmt(stmt)

    def _stmt(self, stmt) -> None:
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            value = stmt.value
            if value is not None:
                self._check_expr(value)
                if self.is_tainted(value):
                    targets = stmt.targets if isinstance(
                        stmt, ast.Assign) else [stmt.target]
                    for t in targets:
                        for n in ast.walk(t):
                            if isinstance(n, ast.Name):
                                self.tainted.add(n.id)
            return
        if isinstance(stmt, (ast.If, ast.While)):
            self._check_expr(stmt.test)
            if self.is_tainted(stmt.test):
                kind = "if" if isinstance(stmt, ast.If) else "while"
                self._emit(stmt.lineno, "tracer-branch",
                           f"Python `{kind}` on a tracer-derived value: "
                           f"recompiles per value (or fails to trace) — "
                           f"use lax.cond/lax.while_loop/jnp.where")
            for s in stmt.body + stmt.orelse:
                self._stmt(s)
            return
        if isinstance(stmt, ast.For):
            if self.is_tainted(stmt.iter):
                for n in ast.walk(stmt.target):
                    if isinstance(n, ast.Name):
                        self.tainted.add(n.id)
            self._check_expr(stmt.iter)
            for s in stmt.body + stmt.orelse:
                self._stmt(s)
            return
        if isinstance(stmt, (ast.Return, ast.Expr)):
            if stmt.value is not None:
                self._check_expr(stmt.value)
            return
        if isinstance(stmt, ast.With):
            for item in stmt.items:
                self._check_expr(item.context_expr)
            for s in stmt.body:
                self._stmt(s)
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._walk(stmt)
            return
        # default: check expressions, recurse into bodies (except
        # handlers are neither stmt nor expr — recurse explicitly or
        # their bodies would escape the host-sync/branch rules)
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._check_expr(child)
            elif isinstance(child, (ast.stmt, ast.excepthandler)):
                self._stmt(child)

    def _check_expr(self, expr) -> None:
        skip = set()
        for node in ast.walk(expr):
            if id(node) in skip or not isinstance(node, ast.Call):
                continue
            f = node.func
            fname = f.attr if isinstance(f, ast.Attribute) else \
                (f.id if isinstance(f, ast.Name) else None)
            if fname in CALLBACK_FUNCS and node.args:
                skip.update(id(n) for n in ast.walk(node.args[0]))
                continue
            if isinstance(f, ast.Attribute) and f.attr == "item":
                self._emit(node.lineno, "host-sync",
                           ".item() in traced code forces a device "
                           "round trip per call")
            elif isinstance(f, ast.Name) and \
                    f.id in ("int", "float", "bool") and \
                    any(self.is_tainted(a) for a in node.args):
                self._emit(node.lineno, "host-sync",
                           f"{f.id}() of a tracer-derived value in "
                           f"traced code: host sync (or trace error)")
            elif isinstance(f, ast.Attribute) and \
                    _base_name(f) == "np" and f.attr in NP_SYNC_FUNCS and \
                    any(self.is_tainted(a) for a in node.args):
                self._emit(node.lineno, "host-sync",
                           f"np.{f.attr}() of a device value in traced "
                           f"code: device->host copy per call")
            elif isinstance(f, ast.Attribute) and \
                    _base_name(f) == "jnp" and f.attr in JNP_FLOAT_CTORS:
                has_float = any(
                    isinstance(n, ast.Constant) and isinstance(n.value,
                                                               float)
                    for a in node.args for n in ast.walk(a))
                has_dtype = any(kw.arg == "dtype" for kw in node.keywords)
                if has_float and not has_dtype:
                    self._emit(node.lineno, "float-literal-dtype",
                               f"float literal into jnp.{f.attr} with no "
                               f"dtype: promotes to float64 under x64")


def jax_lint(repo=None, roots=JAX_ROOTS, jit_sites=None,
             traced_roots=None) -> List[Finding]:
    """Run the pass; returns unsuppressed findings (empty == clean)."""
    if repo is None:
        repo = Path(__file__).resolve().parents[2]
    return JaxPass(Path(repo), roots, jit_sites, traced_roots).run()
