"""Placement manifest for DataplaneTables fields and TableBuilder staging.

Companion to the ``--uploads`` pass (`tools/analysis/uploadlint.py`), in the
same contract style as `jit_manifest.py`: the dict literals below are the
reviewed source of truth, and the pass diffs them against what the AST of
``vpp_tpu/pipeline/tables.py`` actually says.  Adding a `DataplaneTables`
field without deciding how it ships (which `_UPLOAD_GROUPS` entry re-uploads
it, or which carried-by-reference ledger exempts it) is a finding -- the
failure mode is otherwise silent: either a stale device plane (field staged
but never re-shipped) or a full-table re-upload on every swap.

Three tables:

- ``FIELD_PLACEMENTS``: every `DataplaneTables` field -> exactly one
  placement, ``group:<name>`` (member of that `_UPLOAD_GROUPS` entry; the
  builder re-stages + re-uploads it when the group is dirty) or
  ``ledger:<NAME>`` (carried by reference across swaps -- session state,
  telemetry counters, sweep cursors -- never re-staged from host).
- ``STAGED_ATTRS``: TableBuilder staging attribute -> upload group.  A
  mutator that writes one of these must mark that group dirty on every
  non-raising path, or the next `to_device()` ships a stale plane.
- ``EXEMPT_METHODS``: TableBuilder methods excluded from the mark-dataflow
  check, each with the invariant that makes the exemption sound.
"""

from typing import Dict

# --- field -> placement (generated from tables.py, then reviewed) ---------
# Keep in DataplaneTables declaration order so diffs stay readable.
FIELD_PLACEMENTS: Dict[str, str] = {
    "acl_src_net": "group:acl",
    "acl_src_mask": "group:acl",
    "acl_dst_net": "group:acl",
    "acl_dst_mask": "group:acl",
    "acl_proto": "group:acl",
    "acl_sport_lo": "group:acl",
    "acl_sport_hi": "group:acl",
    "acl_dport_lo": "group:acl",
    "acl_dport_hi": "group:acl",
    "acl_action": "group:acl",
    "acl_nrules": "group:acl",
    "acl_bv_bnd_src": "group:acl",
    "acl_bv_bnd_dst": "group:acl",
    "acl_bv_bnd_sport": "group:acl",
    "acl_bv_bnd_dport": "group:acl",
    "acl_bv_nbnd": "group:acl",
    "acl_bv_src": "group:acl",
    "acl_bv_dst": "group:acl",
    "acl_bv_sport": "group:acl",
    "acl_bv_dport": "group:acl",
    "acl_bv_proto": "group:acl",
    "glb_src_net": "group:glb",
    "glb_src_mask": "group:glb",
    "glb_dst_net": "group:glb",
    "glb_dst_mask": "group:glb",
    "glb_proto": "group:glb",
    "glb_sport_lo": "group:glb",
    "glb_sport_hi": "group:glb",
    "glb_dport_lo": "group:glb",
    "glb_dport_hi": "group:glb",
    "glb_action": "group:glb",
    "glb_nrules": "group:glb",
    "glb_mxu_coeff": "group:glb",
    "glb_mxu_k": "group:glb",
    "glb_mxu_act": "group:glb",
    "glb_bv_bnd_src": "group:glb_bv",
    "glb_bv_bnd_dst": "group:glb_bv",
    "glb_bv_bnd_sport": "group:glb_bv",
    "glb_bv_bnd_dport": "group:glb_bv",
    "glb_bv_nbnd": "group:glb_bv",
    "glb_bv_src": "group:glb_bv",
    "glb_bv_dst": "group:glb_bv",
    "glb_bv_sport": "group:glb_bv",
    "glb_bv_dport": "group:glb_bv",
    "glb_bv_proto": "group:glb_bv",
    "glb_ml_w1": "group:ml",
    "glb_ml_b1": "group:ml",
    "glb_ml_s1": "group:ml",
    "glb_ml_w2": "group:ml",
    "glb_ml_b2": "group:ml",
    "glb_ml_f_feat": "group:ml",
    "glb_ml_f_thresh": "group:ml",
    "glb_ml_f_leaf": "group:ml",
    "glb_ml_thresh": "group:ml",
    "glb_ml_action": "group:ml",
    "glb_ml_rl_shift": "group:ml",
    "glb_ml_version": "group:ml",
    "if_type": "group:if",
    "if_local_table": "group:if",
    "if_apply_global": "group:if",
    "fib_prefix": "group:fib",
    "fib_mask": "group:fib",
    "fib_plen": "group:fib",
    "fib_tx_if": "group:fib",
    "fib_disp": "group:fib",
    "fib_next_hop": "group:fib",
    "fib_node_id": "group:fib",
    "fib_snat": "group:fib",
    "fib_grp": "group:fib",
    "fib_lpm_p0": "group:fib",
    "fib_lpm_p1": "group:fib",
    "fib_lpm_p2": "group:fib",
    "fib_lpm_p3": "group:fib",
    "fib_lpm_p4": "group:fib",
    "fib_lpm_p5": "group:fib",
    "fib_lpm_p6": "group:fib",
    "fib_lpm_p7": "group:fib",
    "fib_lpm_p8": "group:fib",
    "fib_lpm_p9": "group:fib",
    "fib_lpm_p10": "group:fib",
    "fib_lpm_p11": "group:fib",
    "fib_lpm_p12": "group:fib",
    "fib_lpm_p13": "group:fib",
    "fib_lpm_p14": "group:fib",
    "fib_lpm_p15": "group:fib",
    "fib_lpm_p16": "group:fib",
    "fib_lpm_p17": "group:fib",
    "fib_lpm_p18": "group:fib",
    "fib_lpm_p19": "group:fib",
    "fib_lpm_p20": "group:fib",
    "fib_lpm_p21": "group:fib",
    "fib_lpm_p22": "group:fib",
    "fib_lpm_p23": "group:fib",
    "fib_lpm_p24": "group:fib",
    "fib_lpm_p25": "group:fib",
    "fib_lpm_p26": "group:fib",
    "fib_lpm_p27": "group:fib",
    "fib_lpm_p28": "group:fib",
    "fib_lpm_p29": "group:fib",
    "fib_lpm_p30": "group:fib",
    "fib_lpm_p31": "group:fib",
    "fib_lpm_p32": "group:fib",
    "fib_lpm_cnt": "group:fib",
    "fib_lpm_hint": "group:fib",
    "fib_grp_nh": "group:fib",
    "fib_grp_tx_if": "group:fib",
    "fib_grp_node": "group:fib",
    "fib_grp_n": "group:fib",
    "fib_ecmp_c": "ledger:FIB_STATE_FIELDS",
    "sess_src": "ledger:SESSION_FIELDS",
    "sess_dst": "ledger:SESSION_FIELDS",
    "sess_ports": "ledger:SESSION_FIELDS",
    "sess_proto": "ledger:SESSION_FIELDS",
    "sess_valid": "ledger:SESSION_FIELDS",
    "sess_time": "ledger:SESSION_FIELDS",
    "sess_max_age": "group:config",
    "nat_ext_ip": "group:nat",
    "nat_ext_port": "group:nat",
    "nat_proto": "group:nat",
    "nat_boff": "group:nat",
    "nat_bcnt": "group:nat",
    "nat_total_w": "group:nat",
    "nat_self_snat": "group:nat",
    "natb_ip": "group:nat",
    "natb_port": "group:nat",
    "natb_cumw": "group:nat",
    "nat_snat_ip": "group:nat",
    "natsess_a": "ledger:SESSION_FIELDS",
    "natsess_b": "ledger:SESSION_FIELDS",
    "natsess_ports": "ledger:SESSION_FIELDS",
    "natsess_proto": "ledger:SESSION_FIELDS",
    "natsess_valid": "ledger:SESSION_FIELDS",
    "natsess_time": "ledger:SESSION_FIELDS",
    "natsess_orig_ip": "ledger:SESSION_FIELDS",
    "natsess_orig_port": "ledger:SESSION_FIELDS",
    "natsess_src_ip": "ledger:SESSION_FIELDS",
    "natsess_sport": "ledger:SESSION_FIELDS",
    "natsess_kind": "ledger:SESSION_FIELDS",
    "sess_sweep_cursor": "ledger:SESSION_FIELDS",
    "natsess_sweep_cursor": "ledger:SESSION_FIELDS",
    "tel_lat_hist": "ledger:TELEMETRY_FIELDS",
    "tel_sketch": "ledger:TELEMETRY_FIELDS",
    "tel_sketched": "ledger:TELEMETRY_FIELDS",
    "tel_top_key": "ledger:TELEMETRY_FIELDS",
    "tel_top_src": "ledger:TELEMETRY_FIELDS",
    "tel_top_dst": "ledger:TELEMETRY_FIELDS",
    "tel_top_ports": "ledger:TELEMETRY_FIELDS",
    "tel_top_cnt": "ledger:TELEMETRY_FIELDS",
    "tnt_pfx_net": "group:tenant",
    "tnt_pfx_mask": "group:tenant",
    "tnt_pfx_id": "group:tenant",
    "tnt_rate": "group:tenant",
    "tnt_burst": "group:tenant",
    "tnt_sess_base": "group:tenant",
    "tnt_sess_mask": "group:tenant",
    "tnt_nat_base": "group:tenant",
    "tnt_nat_mask": "group:tenant",
    "glb_ml_tnt_mode": "group:tenant",
    "glb_ml_tnt_thresh": "group:tenant",
    "tnt_vni": "group:tenant",
    "tnt_tokens": "ledger:TENANCY_STATE_FIELDS",
    "tnt_tok_time": "ledger:TENANCY_STATE_FIELDS",
    "tnt_rx_c": "ledger:TENANCY_STATE_FIELDS",
    "tnt_tx_c": "ledger:TENANCY_STATE_FIELDS",
    "tnt_rl_c": "ledger:TENANCY_STATE_FIELDS",
    "tnt_qf_c": "ledger:TENANCY_STATE_FIELDS",
    "ovl_vtep_ip": "group:config",
    "svc_vip_ip": "group:svc",
    "svc_vip_port": "group:svc",
    "svc_vip_proto": "group:svc",
    "svc_vip_snat": "group:svc",
    "svc_bk_n": "group:svc",
    "svc_bk_ip": "group:svc",
    "svc_bk_port": "group:svc",
}

# --- TableBuilder staging attribute -> upload group -----------------------
# Writes to these (attribute assign, subscript store, or in-place update)
# inside a TableBuilder method must be followed, on every non-raising path,
# by a mark of the mapped group (self._mark(g) / self._dirty.add(g) /
# self._dirty.update(..)).  Host-only metadata attrs (caches, prev-refs,
# timing) are deliberately absent: writing them cannot stale a device plane.
STAGED_ATTRS: Dict[str, str] = {
    # acl: per-table rule columns + compiled per-table bit-planes
    "acl": "acl",
    "acl_nrules": "acl",
    "acl_bv": "acl",
    # glb: packed global rule columns (+ MXU operand re-pack)
    "glb": "glb",
    "glb_nrules": "glb",
    "glb_mxu": "glb",
    # glb_bv: compiled global bit-vector planes
    "glb_bv": "glb_bv",
    # ml: model weights/config staging dict + kind tag
    "ml": "ml",
    "ml_kind": "ml",
    # tenant: tenancy table + its restaged column dict
    "tenants": "tenant",
    "tnt": "tenant",
    # if: interface typing / table binding rows
    "if_type": "if",
    "if_local_table": "if",
    "if_apply_global": "if",
    # fib: route slots, nh-groups, and the LPM restage products
    "fib_prefix": "fib",
    "fib_plen": "fib",
    "fib_mask": "fib",
    "fib_next_hop": "fib",
    "fib_tx_if": "fib",
    "fib_node_id": "fib",
    "fib_disp": "fib",
    "fib_snat": "fib",
    "fib_grp": "fib",
    "nh_groups": "fib",
    "fib_grp_nh": "fib",
    "fib_grp_tx_if": "fib",
    "fib_grp_node": "fib",
    "fib_grp_n": "fib",
    "lpm_planes": "fib",
    "lpm_cnt": "fib",
    "lpm_counts": "fib",
    "lpm_hint": "fib",
    # nat: static mapping rows + backend pools + SNAT ip
    "nat_proto": "nat",
    "nat_ext_ip": "nat",
    "nat_ext_port": "nat",
    "nat_boff": "nat",
    "nat_bcnt": "nat",
    "nat_total_w": "nat",
    "nat_self_snat": "nat",
    "natb_ip": "nat",
    "natb_port": "nat",
    "natb_cumw": "nat",
    "nat_snat_ip": "nat",
    # config: scalar knobs shipped with the config group
    "ovl_vtep_ip": "config",
    # svc: service LB staging + its restaged column dict
    "services": "svc",
    "svc": "svc",
}

# --- methods exempt from the mark-dataflow check --------------------------
EXEMPT_METHODS: Dict[str, str] = {
    "state_snapshot": (
        "read-only apart from the _restage_lpm refresh; snapshots staging, "
        "never stales it"),
    "_restage_lpm": (
        "lazy LPM restage: only reachable with 'fib' already dirty "
        "(_mark_fib_slots adds the plen to _lpm_dirty_lens AND marks 'fib' "
        "atomically; state_restore resets the set), so the group mark "
        "already happened at the add_route/del_route site"),
    "state_restore": (
        "rollback path, audited ISSUE 20: every snapshot->restore span "
        "(txn.apply_txn, cli config-replay, configurator "
        "_render_svc_locked) runs under the dataplane commit lock with "
        "the restore BEFORE the aborted swap, so no to_device() can "
        "intervene and _dirty only grew since the snapshot; the "
        "`_dirty |= snap['dirty']` union plus the explicit full "
        "fib/bv re-dirty and _svc_prev/_fib_prev resets covers every "
        "group whose staging can diverge from the device cache"),
}
