"""The ``--uploads`` upload-group consistency pass (ISSUE 20).

The incremental-upload contract of ``vpp_tpu/pipeline/tables.py``: every
`DataplaneTables` field ships through exactly one `_UPLOAD_GROUPS` entry,
or is carried by reference across swaps via a state ledger
(`SESSION_FIELDS`, `TELEMETRY_FIELDS`, `TENANCY_STATE_FIELDS`,
`FIB_STATE_FIELDS` — the sweep cursors live inside `SESSION_FIELDS`).
A `TableBuilder` mutator that writes a staged attribute must mark the
owning group dirty on every non-raising path, or the next `to_device()`
silently ships a stale device plane — the PR-4/PR-19 hand-review hazard.

Rules (docs/STATIC_ANALYSIS.md catalog):

* ``upload-field-unplaced``   — DataplaneTables field in no group and no
  ledger: nobody decided how it ships.
* ``upload-field-multi``      — field claimed by more than one placement.
* ``upload-group-stale``      — a group/ledger entry names a field that
  no longer exists on DataplaneTables.
* ``upload-manifest-missing`` — field absent from
  `upload_manifest.FIELD_PLACEMENTS` (a new field needs a reviewed
  placement decision, not just a group edit).
* ``upload-manifest-stale``   — manifest entry for a non-existent field.
* ``upload-manifest-mismatch``— manifest placement disagrees with what
  tables.py actually says.
* ``upload-mark-missing``     — a TableBuilder method writes a staged
  attribute (`upload_manifest.STAGED_ATTRS`) and some non-raising path
  reaches an exit without marking that attribute's group dirty.  The
  dataflow follows `self._mark(g)`, `self._dirty.add/update(...)`,
  whole-set re-marks (`self._dirty = set(_UPLOAD_GROUPS)`), and
  self-method calls (helper summaries to fixpoint); branches merge by
  union of still-pending groups; paths ending in `raise` are dropped
  (the builder re-stages on the next successful mutation).
* ``upload-dirty-field-foreign`` — a literal field added to a sub-dirty
  set (`_fib_dirty`, `_bv_dirty`) that is not a member of the owning
  group: it would never be consulted by the incremental uploader.
* ``upload-extern-write``     — staged builder attributes written from
  outside TableBuilder (``dp.builder.if_local_table[...] = ...``):
  external writers bypass the dirty-marking discipline entirely and
  must go through a mutator.
* ``upload-exempt-stale``     — `EXEMPT_METHODS` names a method that no
  longer exists.

Suppress one line with ``# upload-ok: <reason>``.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, List, Optional, Set, Tuple

from analysis.common import Finding, iter_source_files, parse_suppressions

TABLES_REL = "vpp_tpu/pipeline/tables.py"
# roots scanned for external writes to staged builder attributes
UPLOAD_ROOTS = ("vpp_tpu", "bench.py")

LEDGER_NAMES = ("SESSION_FIELDS", "TELEMETRY_FIELDS",
                "TENANCY_STATE_FIELDS", "FIB_STATE_FIELDS")

# sub-dirty sets -> the group whose fields they may name
SUB_DIRTY = {"_fib_dirty": "fib", "_bv_dirty": "glb_bv"}

# calls on a staged dict/list attr that mutate it in place
_MUTATING = {"pop", "clear", "update", "append", "extend", "add",
             "remove", "setdefault", "insert"}

_RAISED, _RETURNED = "raised", "returned"


def _self_attr(expr) -> Optional[str]:
    """'x' for ``self.x`` — None otherwise (first hop only)."""
    parts = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name) and expr.id == "self" and parts:
        return parts[-1]
    return None


def _peel(target):
    """Strip Subscript layers: ``self.acl[t]["src"][i]`` -> ``self.acl``."""
    while isinstance(target, ast.Subscript):
        target = target.value
    return target


def _str_elts(node) -> Optional[List[str]]:
    """Literal string elements of a Constant/Tuple/List/Set, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.append(e.value)
            else:
                return None
        return out
    return None


class _TablesModel:
    """What the AST of tables.py actually declares."""

    def __init__(self):
        self.fields: Dict[str, int] = {}          # field -> lineno
        self.groups: Dict[str, List[str]] = {}    # group -> fields
        self.groups_line = 1
        self.ledgers: Dict[str, List[str]] = {}   # ledger -> fields
        self.field_sets: Dict[str, Set[str]] = {}  # module field listings
        self.builder: Optional[ast.ClassDef] = None


def _load_model(tree: ast.Module) -> _TablesModel:
    model = _TablesModel()

    def record(name, value, lineno):
        if name == "_UPLOAD_GROUPS" and isinstance(value, ast.Dict):
            model.groups_line = lineno
            for k, v in zip(value.keys, value.values):
                if isinstance(k, ast.Constant):
                    model.groups[k.value] = _str_elts(v) or []
        elif name in LEDGER_NAMES and isinstance(value, ast.Dict):
            model.ledgers[name] = [
                k.value for k in value.keys if isinstance(k, ast.Constant)]
        elif isinstance(value, (ast.Tuple, ast.List)):
            elts = _str_elts(value)
            if elts is not None:
                model.field_sets[name] = set(elts)
        elif isinstance(value, ast.Dict):
            acc: Set[str] = set()
            for v in value.values:
                elts = _str_elts(v)
                if elts is None:
                    return
                acc.update(elts)
            model.field_sets[name] = acc

    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            if node.name == "DataplaneTables":
                for st in node.body:
                    if isinstance(st, ast.AnnAssign) and \
                            isinstance(st.target, ast.Name):
                        model.fields[st.target.id] = st.lineno
            elif node.name == "TableBuilder":
                model.builder = node
        elif isinstance(node, ast.AnnAssign) and \
                isinstance(node.target, ast.Name) and node.value is not None:
            record(node.target.id, node.value, node.lineno)
        elif isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            record(node.targets[0].id, node.value, node.lineno)
    return model


class _Summary:
    """Effect of calling a TableBuilder method on the pending state."""

    def __init__(self):
        # group -> (attr, line): written-but-unmarked at some exit
        self.pending: Dict[str, Tuple[str, int]] = {}
        self.marks: Set[str] = set()   # marked on every non-raising path

    def key(self):
        return (frozenset(self.pending), frozenset(self.marks))


class _State:
    def __init__(self):
        self.pending: Dict[str, Tuple[str, int]] = {}
        self.marked: Set[str] = set()

    def copy(self) -> "_State":
        st = _State()
        st.pending = dict(self.pending)
        st.marked = set(self.marked)
        return st


def _merge(states: List[_State]) -> Optional[_State]:
    live = [s for s in states if s is not None]
    if not live:
        return None
    out = live[0].copy()
    for s in live[1:]:
        for g, site in s.pending.items():
            out.pending.setdefault(g, site)
        out.marked &= s.marked
    # a group pending on ONE branch is pending, even if marked on another
    for g in list(out.marked):
        if g in out.pending:
            out.marked.discard(g)
    return out


class UploadPass:
    def __init__(self, repo: Path, tables_rel: str = TABLES_REL,
                 roots=UPLOAD_ROOTS, manifest=None):
        self.repo = repo
        self.tables_rel = tables_rel
        self.roots = roots
        if manifest is None:
            from analysis import upload_manifest as manifest
        self.placements: Dict[str, str] = dict(manifest.FIELD_PLACEMENTS)
        self.staged: Dict[str, str] = dict(manifest.STAGED_ATTRS)
        self.exempt: Dict[str, str] = dict(manifest.EXEMPT_METHODS)
        self.findings: List[Finding] = []

    # ------------------------------------------------------------------
    def run(self) -> List[Finding]:
        path = self.repo / self.tables_rel
        src = path.read_text()
        try:
            tree = ast.parse(src, filename=self.tables_rel)
        except SyntaxError:
            return self.findings  # the style pass reports parse failures
        sup = parse_suppressions(src, self.tables_rel)
        self.findings.extend(sup.problems)
        model = _load_model(tree)
        self._check_placements(model, sup)
        if model.builder is not None:
            self._check_marks(model, sup)
        self._check_extern_writes(sup_tables=sup)
        return self.findings

    def _emit(self, relpath, line, rule, msg, sup) -> None:
        if line in sup.upload:
            return
        self.findings.append(Finding(relpath, line, rule, msg))

    # --- placement rules ----------------------------------------------
    def _check_placements(self, model: _TablesModel, sup) -> None:
        rel = self.tables_rel
        placed: Dict[str, List[str]] = {}
        for g, fields in model.groups.items():
            for f in fields:
                placed.setdefault(f, []).append(f"group:{g}")
        for ledger, fields in model.ledgers.items():
            for f in fields:
                placed.setdefault(f, []).append(f"ledger:{ledger}")

        for f, line in model.fields.items():
            got = placed.get(f, [])
            if not got:
                self._emit(rel, line, "upload-field-unplaced",
                           f"DataplaneTables.{f} is in no _UPLOAD_GROUPS "
                           f"entry and no state ledger: decide how it "
                           f"ships (stale-plane hazard)", sup)
            elif len(got) > 1:
                self._emit(rel, line, "upload-field-multi",
                           f"DataplaneTables.{f} has {len(got)} "
                           f"placements ({', '.join(sorted(got))}); "
                           f"exactly one owns the upload", sup)
        for f, wheres in placed.items():
            if f not in model.fields:
                self._emit(rel, model.groups_line, "upload-group-stale",
                           f"'{f}' ({wheres[0]}) is not a "
                           f"DataplaneTables field any more", sup)

        # manifest <-> AST diff
        for f, line in model.fields.items():
            want = placed.get(f, [None])[0]
            have = self.placements.get(f)
            if have is None:
                self._emit(rel, line, "upload-manifest-missing",
                           f"DataplaneTables.{f} has no entry in "
                           f"tools/analysis/upload_manifest.py "
                           f"FIELD_PLACEMENTS: record the reviewed "
                           f"placement decision", sup)
            elif want is not None and have != want:
                self._emit(rel, line, "upload-manifest-mismatch",
                           f"manifest places {f} at '{have}' but "
                           f"tables.py says '{want}'", sup)
        for f in sorted(self.placements):
            if f not in model.fields:
                self._emit(rel, model.groups_line, "upload-manifest-stale",
                           f"FIELD_PLACEMENTS entry '{f}' is not a "
                           f"DataplaneTables field: drop it", sup)

    # --- mark-dataflow over TableBuilder ------------------------------
    def _check_marks(self, model: _TablesModel, sup) -> None:
        cls = model.builder
        methods = {m.name: m for m in cls.body
                   if isinstance(m, ast.FunctionDef)}
        for name in sorted(self.exempt):
            if name not in methods:
                self._emit(self.tables_rel, cls.lineno,
                           "upload-exempt-stale",
                           f"EXEMPT_METHODS names TableBuilder.{name}() "
                           f"which does not exist", sup)
        summaries: Dict[str, _Summary] = {n: _Summary() for n in methods}
        self._sub_dirty_findings: List[Tuple[int, str]] = []
        for _ in range(8):  # fixpoint over helper summaries
            changed = False
            for name, m in methods.items():
                if name == "__init__" or name in self.exempt:
                    continue
                new = self._analyze_method(m, model, summaries,
                                           emit=False)
                if new.key() != summaries[name].key():
                    summaries[name] = new
                    changed = True
            if not changed:
                break
        seen: Set[Tuple[str, str, int]] = set()
        for name, m in sorted(methods.items()):
            if name == "__init__" or name in self.exempt:
                continue
            # private helpers propagate their pending groups to callers
            # (the caller's call line is the finding anchor); only
            # public mutators must mark on every path themselves
            emit = not name.startswith("_")
            summ = self._analyze_method(m, model, summaries, emit=emit,
                                        seen=seen, sup=sup)
            summaries[name] = summ
        for line, msg in sorted(set(self._sub_dirty_findings)):
            self._emit(self.tables_rel, line,
                       "upload-dirty-field-foreign", msg, sup)

    def _analyze_method(self, method, model, summaries, emit,
                        seen=None, sup=None) -> _Summary:
        exits: List[_State] = []
        all_groups = set(model.groups)

        def mark(st: _State, group: str) -> None:
            st.pending.pop(group, None)
            st.marked.add(group)

        def staged_write(st: _State, target, lineno) -> None:
            if isinstance(target, (ast.Tuple, ast.List)):
                for e in target.elts:
                    staged_write(st, e, lineno)
                return
            base = _peel(target)
            attr = _self_attr(base)
            if attr in self.staged:
                st.pending.setdefault(self.staged[attr], (attr, lineno))

        def handle_call(st: _State, call: ast.Call) -> None:
            f = call.func
            if not isinstance(f, ast.Attribute):
                return
            owner = _self_attr(f.value)
            # self._mark("g")
            if _self_attr(f) == "_mark" and call.args:
                lits = _str_elts(call.args[0])
                if lits:
                    mark(st, lits[0])
                return
            # self._dirty.add/update(...)
            if owner == "_dirty" and f.attr in ("add", "update"):
                for a in call.args:
                    lits = _str_elts(a)
                    if lits is None:
                        if isinstance(a, ast.Call) and \
                                isinstance(a.func, ast.Name) and \
                                a.func.id == "set" and a.args and \
                                isinstance(a.args[0], ast.Name) and \
                                a.args[0].id == "_UPLOAD_GROUPS":
                            for g in all_groups:
                                mark(st, g)
                        continue
                    for g in lits:
                        mark(st, g)
                return
            # sub-dirty field hygiene: _fib_dirty/_bv_dirty.add/update
            if owner in SUB_DIRTY and f.attr in ("add", "update"):
                group = SUB_DIRTY[owner]
                members = set(model.groups.get(group, ()))
                for a in call.args:
                    lits = _str_elts(a)
                    if lits is None:
                        node = a
                        while isinstance(node, ast.Subscript):
                            node = node.value
                        if isinstance(node, ast.Name) and \
                                node.id in model.field_sets:
                            lits = sorted(model.field_sets[node.id])
                        else:
                            continue
                    for fld in lits:
                        if members and fld not in members:
                            self._sub_dirty_findings.append((
                                call.lineno,
                                f"'{fld}' added to self.{owner} but it "
                                f"is not in _UPLOAD_GROUPS['{group}']: "
                                f"the incremental uploader will never "
                                f"consult it"))
                return
            # in-place mutation of a staged dict/list: self.ml.clear()
            fattr = _self_attr(f)
            if fattr is None:
                return
            if owner in self.staged and f.attr in _MUTATING:
                st.pending.setdefault(self.staged[owner],
                                      (owner, call.lineno))
                return
            # self.helper(...) -> apply its summary
            if owner is None and f.attr in summaries and \
                    isinstance(f.value, ast.Name) and f.value.id == "self":
                summ = summaries[f.attr]
                for g, (attr, _line) in summ.pending.items():
                    st.pending.setdefault(g, (attr, call.lineno))
                for g in summ.marks:
                    mark(st, g)

        def scan_expr(st: _State, expr) -> None:
            for node in ast.walk(expr):
                if isinstance(node, ast.Call):
                    handle_call(st, node)

        def flow(stmts, st: _State):
            """Returns the fall-through state, or a sentinel."""
            for s in stmts:
                if st in (_RAISED, _RETURNED):
                    return st
                if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                    continue
                if isinstance(s, ast.Return):
                    if s.value is not None:
                        scan_expr(st, s.value)
                    exits.append(st)
                    return _RETURNED
                if isinstance(s, ast.Raise):
                    return _RAISED
                if isinstance(s, (ast.Assign, ast.AugAssign,
                                  ast.AnnAssign)):
                    value = s.value
                    if value is not None:
                        scan_expr(st, value)
                    targets = s.targets if isinstance(s, ast.Assign) \
                        else [s.target]
                    # whole-set re-mark: self._dirty = set(_UPLOAD_GROUPS)
                    tattr = _self_attr(targets[0]) if targets else None
                    if tattr == "_dirty" and isinstance(value, ast.Call) \
                            and isinstance(value.func, ast.Name) \
                            and value.func.id == "set" and value.args \
                            and isinstance(value.args[0], ast.Name) \
                            and value.args[0].id == "_UPLOAD_GROUPS":
                        for g in all_groups:
                            mark(st, g)
                        continue
                    for t in targets:
                        staged_write(st, t, s.lineno)
                    continue
                if isinstance(s, ast.Delete):
                    for t in s.targets:
                        staged_write(st, t, s.lineno)
                    continue
                if isinstance(s, ast.Expr):
                    scan_expr(st, s.value)
                    continue
                if isinstance(s, ast.If):
                    scan_expr(st, s.test)
                    a = flow(s.body, st.copy())
                    b = flow(s.orelse, st.copy())
                    nxt = _merge([x if isinstance(x, _State) else None
                                  for x in (a, b)])
                    if nxt is None:
                        return _RETURNED if _RETURNED in (a, b) \
                            else _RAISED
                    st = nxt
                    continue
                if isinstance(s, (ast.For, ast.While)):
                    if isinstance(s, ast.For):
                        scan_expr(st, s.iter)
                    else:
                        scan_expr(st, s.test)
                    body = flow(s.body + s.orelse, st.copy())
                    nxt = _merge([st, body if isinstance(body, _State)
                                  else None])
                    st = nxt if nxt is not None else st
                    continue
                if isinstance(s, ast.With):
                    for item in s.items:
                        scan_expr(st, item.context_expr)
                    r = flow(s.body, st)
                    if r in (_RAISED, _RETURNED):
                        return r
                    st = r
                    continue
                if isinstance(s, ast.Try):
                    body = flow(s.body, st.copy())
                    body_st = body if isinstance(body, _State) else None
                    # a handler may run with any prefix of the body done
                    h_entry = _merge([st, body_st]) or st
                    outs = [body_st]
                    for h in s.handlers:
                        outs.append(
                            r if isinstance(
                                r := flow(h.body, h_entry.copy()),
                                _State) else None)
                    nxt = _merge(outs)
                    if nxt is None:
                        return body if body in (_RAISED, _RETURNED) \
                            else _RAISED
                    r = flow(s.finalbody, nxt)
                    if r in (_RAISED, _RETURNED):
                        return r
                    st = r
                    continue
                if isinstance(s, (ast.Assert,)):
                    scan_expr(st, s.test)
                    continue
                for node in ast.walk(s):
                    if isinstance(node, ast.Call):
                        handle_call(st, node)
            return st

        end = flow(method.body, _State())
        if isinstance(end, _State):
            exits.append(end)

        summ = _Summary()
        if exits:
            summ.marks = set.intersection(*(e.marked for e in exits))
            for e in exits:
                for g, site in e.pending.items():
                    summ.pending.setdefault(g, site)
            summ.marks -= set(summ.pending)
        if emit:
            for g, (attr, line) in sorted(summ.pending.items()):
                key = (method.name, g, line)
                if key in seen:
                    continue
                seen.add(key)
                self._emit(
                    self.tables_rel, line, "upload-mark-missing",
                    f"TableBuilder.{method.name}() writes staged attr "
                    f"self.{attr} (group '{g}') but a path reaches an "
                    f"exit without marking the group dirty: the next "
                    f"to_device() ships a stale plane", sup)
        return summ

    # --- external writers ---------------------------------------------
    def _check_extern_writes(self, sup_tables) -> None:
        for relpath, path in iter_source_files(self.repo, self.roots):
            if relpath == self.tables_rel:
                continue
            src = path.read_text()
            try:
                tree = ast.parse(src, filename=relpath)
            except SyntaxError:
                continue
            sup = parse_suppressions(src, relpath)
            self.findings.extend(sup.problems)
            for node in ast.walk(tree):
                targets = []
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                    targets = [node.target]
                elif isinstance(node, ast.Delete):
                    targets = node.targets
                for t in targets:
                    base = _peel(t)
                    if not isinstance(base, ast.Attribute) or \
                            base.attr not in self.staged:
                        continue
                    owner = base.value
                    if isinstance(owner, ast.Attribute) and \
                            owner.attr == "builder":
                        self._emit(
                            relpath, base.lineno, "upload-extern-write",
                            f"write to builder.{base.attr} (staged, "
                            f"group '{self.staged[base.attr]}') from "
                            f"outside TableBuilder bypasses dirty-"
                            f"marking: go through a mutator", sup)


def uploads_lint(repo=None, tables_rel: str = TABLES_REL,
                 roots=UPLOAD_ROOTS, manifest=None) -> List[Finding]:
    """Run the pass; returns unsuppressed findings (empty == clean)."""
    if repo is None:
        repo = Path(__file__).resolve().parents[2]
    return UploadPass(Path(repo), tables_rel, roots, manifest).run()
