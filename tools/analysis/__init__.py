"""In-tree static-analysis framework (the metalinter CI stage analog,
README.md:36-40 / Dockerfile.metalinter — rebuilt as project-specific
AST passes for the two bug classes generic linters miss here):

* ``style``    — base hygiene (tools/analysis/imports.py): parse, unused
  imports, bare except, tabs/trailing whitespace, mutable defaults,
  ``== True/False/None`` comparisons.
* ``jax``      — tracer/recompile hygiene (tools/analysis/jaxlint.py):
  host syncs inside jitted code, Python control flow on tracer-derived
  values, per-instance jit closures and lru_cache factory hazards (the
  PR-4 fresh-closure bug class), float64 literal drift, and a
  jit-registry manifest so every jitted entry point is enumerated.
* ``threads``  — lock discipline (tools/analysis/threadlint.py): per
  class, attributes written under ``with self._lock`` must be accessed
  under it everywhere; lock-nesting order must be acyclic.
* ``metrics`` / ``counters`` / ``tables`` — registry and table
  invariants (tools/analysis/registries.py; import jax, so they only
  run when asked for).

``tools/lint.py`` is the CLI; tier-1 invokes the passes through
tests/test_analysis.py + tests/test_exposition.py + tests/test_acl_bv.py.
Suppression syntax and the rule catalog: docs/STATIC_ANALYSIS.md.
"""

from analysis.common import Finding, iter_source_files, parse_suppressions
from analysis.imports import ImportCollector, style_problems
from analysis.jaxlint import jax_lint
from analysis.threadlint import threads_lint

__all__ = [
    "Finding",
    "ImportCollector",
    "iter_source_files",
    "jax_lint",
    "parse_suppressions",
    "style_problems",
    "threads_lint",
]
