"""The ``--donate`` use-after-donate pass (ISSUE 20).

``donate_argnums`` hands buffer ownership to the jit: after the call the
donated arrays are invalidated, and a later read sees garbage (or a
runtime error at best) — the PR-8 hazard that made `checkpoint_sessions`
copy session columns instead of exposing the pump's live carry.  The
ring/packed paths thread donated carries on purpose; this pass checks
that discipline mechanically from the jit manifest's donation registry.

Rules (docs/STATIC_ANALYSIS.md catalog):

* ``use-after-donate``     — a name passed in a donated position of a
  registered donating call (`jit_manifest.DONATING_CALLS`) is read
  later in the same scope with no rebind in between.  Both straight-
  line reads after the call and loop-carried reads (the next
  iteration's call re-donates the same name) are checked; a rebind
  anywhere on the path (including the donating statement's own
  assignment targets — the threading idiom) clears the hazard.
  Donated values re-exposed through the sanctioned copy points
  (`checkpoint_sessions` / `_serve_ckpt` ``jnp.copy``, the stager
  hand-off) live in other scopes and take fresh references, so they
  never trip this rule.  Suppress one line with
  ``# donate-ok: <reason>``.
* ``donate-unregistered``  — a call with a non-empty literal
  ``donate_argnums`` whose (file, enclosing scope) is not in
  `jit_manifest.DONATED_JIT_SITES`: donation without a registered
  ownership story.
* ``donate-site-stale``    — a DONATED_JIT_SITES / DONATING_CALLS entry
  that no longer resolves (scope gone, no matching call): drop or fix.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, List, Optional, Set, Tuple

from analysis.common import Finding, iter_source_files, parse_suppressions

DONATE_ROOTS = ("vpp_tpu", "bench.py", "tests")


def _callee_repr(func) -> Optional[str]:
    """'step' for Name, 'self._step' / 'dp.process' for Attribute."""
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        base = _callee_repr(func.value)
        return f"{base}.{func.attr}" if base else None
    return None


def _name_events(scope_body, kinds) -> List[Tuple[str, int, str]]:
    """(name, line, 'load'|'store') events in a scope, nested function
    bodies excluded (closures get fresh references at call time; the
    sanctioned copy points live there)."""
    events = []

    def visit(node):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return
        if isinstance(node, ast.Name):
            if isinstance(node.ctx, ast.Load):
                events.append((node.id, node.lineno, "load"))
            elif isinstance(node.ctx, (ast.Store, ast.Del)):
                events.append((node.id, node.lineno, "store"))
        for child in ast.iter_child_nodes(node):
            visit(child)

    for s in scope_body:
        visit(s)
    return [e for e in events if e[2] in kinds]


class DonatePass:
    def __init__(self, repo: Path, roots=DONATE_ROOTS, manifest=None):
        self.repo = repo
        self.roots = roots
        if manifest is None:
            from analysis import jit_manifest as manifest
        self.jit_sites: Dict[Tuple[str, str], str] = dict(
            manifest.DONATED_JIT_SITES)
        self.calls: Dict[Tuple[str, str, str], Tuple[tuple, str]] = dict(
            manifest.DONATING_CALLS)
        self.findings: List[Finding] = []

    def run(self) -> List[Finding]:
        seen_jit: Set[Tuple[str, str]] = set()
        seen_calls: Set[Tuple[str, str, str]] = set()
        for relpath, path in iter_source_files(self.repo, self.roots):
            src = path.read_text()
            try:
                tree = ast.parse(src, filename=relpath)
            except SyntaxError:
                continue
            sup = parse_suppressions(src, relpath)
            self.findings.extend(sup.problems)
            self._scan_file(relpath, tree, sup, seen_jit, seen_calls)
        for key, _reason in sorted(self.jit_sites.items()):
            if key not in seen_jit:
                self.findings.append(Finding(
                    key[0], 1, "donate-site-stale",
                    f"DONATED_JIT_SITES entry {key[1]!r} has no "
                    f"donating jit left in {key[0]}: drop or fix it"))
        for key, _spec in sorted(self.calls.items()):
            if key not in seen_calls:
                self.findings.append(Finding(
                    key[0], 1, "donate-site-stale",
                    f"DONATING_CALLS entry {key[1]!r} -> {key[2]!r} "
                    f"matches no call in {key[0]}: drop or fix it"))
        return self.findings

    # ------------------------------------------------------------------
    def _scan_file(self, relpath, tree, sup, seen_jit, seen_calls):
        def walk(node, stack):
            qual = ".".join(stack) or "<module>"
            self._check_scope(relpath, qual, node.body, sup, seen_jit,
                              seen_calls)
            for ch in node.body:
                if isinstance(ch, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                    walk(ch, stack + [ch.name])
                elif isinstance(ch, ast.ClassDef):
                    for m in ch.body:
                        if isinstance(m, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)):
                            walk(m, stack + [ch.name, m.name])

        walk(tree, [])

    def _check_scope(self, relpath, qual, body, sup, seen_jit,
                     seen_calls):
        # --- donate-unregistered: literal non-empty donate_argnums ----
        def scan_jits(stmts):
            for s in stmts:
                for node in ast.walk(s):
                    if isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        continue  # inner scopes checked separately
                    if not isinstance(node, ast.Call):
                        continue
                    for kw in node.keywords:
                        if kw.arg != "donate_argnums":
                            continue
                        if isinstance(kw.value, (ast.Tuple, ast.List)) \
                                and not kw.value.elts:
                            continue  # empty: nothing donated
                        seen_jit.add((relpath, qual))
                        if (relpath, qual) not in self.jit_sites and \
                                node.lineno not in sup.donate:
                            self.findings.append(Finding(
                                relpath, node.lineno,
                                "donate-unregistered",
                                f"jit with donate_argnums in {qual}() "
                                f"is not registered in jit_manifest."
                                f"DONATED_JIT_SITES: donation needs an "
                                f"ownership story"))

        # only this scope's own statements (nested defs are their own
        # scopes in the walk)
        own = [s for s in body if not isinstance(
            s, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef))]
        scan_jits(own)

        # --- use-after-donate over registered calls -------------------
        entries = {callee: spec for (rp, q, callee), spec
                   in self.calls.items()
                   if rp == relpath and q == qual}
        if not entries:
            return
        events = _name_events(own, ("load", "store"))
        calls = []  # (lineno, callee, donated (argnum, name)s, loop)
        excl = []   # mutually-exclusive (if-body, else-body) line spans

        def record_calls(s, loop):
            span = (s.lineno, s.end_lineno or s.lineno)
            for node in ast.walk(s):
                if isinstance(node, ast.Call):
                    rep = _callee_repr(node.func)
                    if rep in entries:
                        argnums = entries[rep][0]
                        # a *args expansion makes positions after the
                        # star unknowable at the AST level — only track
                        # donated names left of the first Starred
                        star = next(
                            (i for i, a in enumerate(node.args)
                             if isinstance(a, ast.Starred)),
                            len(node.args))
                        names = [
                            (i, node.args[i].id) for i in argnums
                            if i < star
                            and isinstance(node.args[i], ast.Name)]
                        calls.append(
                            (node.lineno, span, rep, names, loop))

        def collect_calls(stmts, loop):
            for s in stmts:
                if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                    continue
                if isinstance(s, (ast.For, ast.While)):
                    collect_calls(s.body + s.orelse,
                                  (s.lineno, s.end_lineno or s.lineno))
                elif isinstance(s, ast.If):
                    record_calls(s.test, loop)
                    if s.body and s.orelse:
                        excl.append((
                            (s.body[0].lineno,
                             s.body[-1].end_lineno or s.body[-1].lineno),
                            (s.orelse[0].lineno,
                             s.orelse[-1].end_lineno
                             or s.orelse[-1].lineno)))
                    collect_calls(s.body + s.orelse, loop)
                elif isinstance(s, ast.With):
                    for item in s.items:
                        record_calls(item.context_expr, loop)
                    collect_calls(s.body, loop)
                elif isinstance(s, ast.Try):
                    collect_calls(s.body + s.orelse + s.finalbody, loop)
                    for h in s.handlers:
                        collect_calls(h.body, loop)
                else:
                    record_calls(s, loop)

        collect_calls(own, None)
        for callee in entries:
            if any(c[2] == callee for c in calls):
                seen_calls.add((relpath, qual, callee))

        stores = {}
        loads = {}
        for name, line, kind in events:
            (stores if kind == "store" else loads).setdefault(
                name, []).append(line)

        def exclusive(a: int, b: int) -> bool:
            return any(
                (p[0] <= a <= p[1] and q[0] <= b <= q[1])
                or (q[0] <= a <= q[1] and p[0] <= b <= p[1])
                for p, q in excl)

        for call_line, (st_lo, st_hi), callee, names, loop in calls:
            for argnum, d in names:
                d_stores = stores.get(d, [])
                d_loads = loads.get(d, [])
                # straight-line: loads after the donating STATEMENT
                # (its own arg reads evaluate before donation lands,
                # its own targets are the threading rebind)
                for r in sorted(d_loads):
                    if r <= st_hi or exclusive(call_line, r):
                        continue
                    if any(st_lo <= s <= r for s in d_stores
                           if not exclusive(call_line, s)):
                        break  # rebound before this (and later) reads
                    self._emit(relpath, r, d, callee, call_line,
                               argnum, sup)
                    break  # one finding per donated name is enough
                # loop-carried: next iteration reads d before a rebind
                if loop is None:
                    continue
                lo, hi = loop
                carried = [r for r in d_loads if lo <= r <= st_hi]
                for r in sorted(carried):
                    killed = any(
                        (st_lo <= s <= hi) or (lo <= s < r)
                        for s in d_stores)
                    if killed:
                        break
                    self._emit(relpath, r, d, callee, call_line,
                               argnum, sup,
                               carried=True)
                    break

    def _emit(self, relpath, line, name, callee, call_line, argnum,
              sup, carried=False) -> None:
        if line in sup.donate:
            return
        how = ("read by the NEXT iteration's donating call"
               if carried else "read after the donating call")
        self.findings.append(Finding(
            relpath, line, "use-after-donate",
            f"'{name}' is donated to {callee}() (argnum {argnum}, "
            f"line {call_line}) and {how}: the buffer is invalidated "
            f"— rebind from the result or jnp.copy before donating"))


def donate_lint(repo=None, roots=DONATE_ROOTS,
                manifest=None) -> List[Finding]:
    """Run the pass; returns unsuppressed findings (empty == clean)."""
    if repo is None:
        repo = Path(__file__).resolve().parents[2]
    return DonatePass(Path(repo), roots, manifest).run()
