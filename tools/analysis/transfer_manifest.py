"""Approved device->host fetch sites for the ``--transfers`` pass.

`TRANSFER_SITES` maps ``(repo-relative path, function qualname)`` to the
reason the fetch is sanctioned.  A qualname of ``"*"`` approves a whole
file (bench captures).  Everything else that materializes a
DataplaneTables-reachable device value on host is a finding — the
"aggregate on host" regression class (PRs 6/8/12).

How to add a site (docs/STATIC_ANALYSIS.md): state WHAT bounds the
fetch (rider-sized, K candidates, drained once per epoch, ...) — "it
was convenient" is not a bound.  Entries that stop resolving are
themselves findings (``transfer-site-stale``).
"""

from typing import Dict, Tuple

TRANSFER_SITES: Dict[Tuple[str, str], str] = {
    ("vpp_tpu/pipeline/persistent.py", "PersistentPump._fetch_loop"): (
        "THE packed-result fetch: one device_get per ring window of "
        "tx/aux riders (+ telemetry rider), never table columns"),
    ("bench.py", "*"): (
        "bench captures: measurement harness, results must land on "
        "host; sections run off the serving path by construction"),
    # --- snapshot drains (PR 8): the sanctioned bulk session fetches --
    ("vpp_tpu/pipeline/snapshot.py", "SessionSnapshotter._drain"): (
        "the periodic session checkpoint drain: amortized over the "
        "snapshot interval, runs on the snapshotter thread off the "
        "dispatch path"),
    ("vpp_tpu/pipeline/snapshot.py", "adopt_bucket_range"): (
        "live migration adopt: fetches SESSION_FIELDS once to splice "
        "the drained bucket range in; bounded by the range size and "
        "migration cadence"),
    ("vpp_tpu/pipeline/snapshot.py", "release_bucket_range"): (
        "live migration release: same bounded range splice as adopt, "
        "invalidating the moved buckets on the source"),
    # --- dataplane snapshots: bounded rider/slot-candidate fetches ----
    ("vpp_tpu/pipeline/dataplane.py", "Dataplane.fib_snapshot"): (
        "fetches fib_ecmp_c only — the ECMP counter column, slot-"
        "bounded, drained at CLI/collector cadence"),
    ("vpp_tpu/pipeline/dataplane.py", "Dataplane.telemetry_snapshot"): (
        "the telemetry rider drain: K-slot candidates + fixed "
        "histogram bins, never table columns (ISSUE 11 design)"),
    ("vpp_tpu/pipeline/dataplane.py", "Dataplane.tenant_snapshot"): (
        "per-tenant counter rows: max_tenants-bounded, collector "
        "cadence"),
    # --- operator debug drains (the VPP `show session` analogs) -------
    ("vpp_tpu/cli.py", "DebugCLI.show_session"): (
        "operator debug page: drains session columns on explicit CLI "
        "request, never on the serving path"),
    ("vpp_tpu/cli.py", "DebugCLI.show_sessions"): (
        "operator debug page: paged session table listing, explicit "
        "CLI request only"),
    ("vpp_tpu/cli.py", "DebugCLI.show_nat44"): (
        "operator debug page: NAT session listing, explicit CLI "
        "request only"),
}
