"""Shared infrastructure of the analysis passes: the Finding record,
suppression-comment parsing and source-tree iteration.

Suppression syntax (docs/STATIC_ANALYSIS.md):

* ``# jax-ok: <reason>``      — suppress jax-pass findings on this line.
* ``# unlocked: <reason>``    — suppress thread-pass findings on this line.
* ``# upload-ok: <reason>``   — suppress upload-pass findings (ISSUE 20).
* ``# transfer-ok: <reason>`` — suppress transfer-pass findings.
* ``# donate-ok: <reason>``   — suppress donate-pass findings.
* ``# noqa``                  — the base style pass's escape (kept from
  the original tools/lint.py).

A suppression WITHOUT a reason is itself a finding (``bare-suppression``):
the annotation is the changelog entry for the next reader, so an empty
one defeats the point.
"""

from __future__ import annotations

import io
import re
import tokenize
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, Iterator, Tuple

SUPPRESSION_RE = re.compile(
    r"#\s*(jax-ok|unlocked|upload-ok|transfer-ok|donate-ok)\b:?[ \t]*(.*)")


def _comment_lines(src: str) -> Dict[int, str]:
    """{lineno: comment text} via the tokenizer, so a suppression token
    inside a STRING LITERAL (help text, log message) never registers.
    Falls back to treating every line as scannable if tokenization
    fails (the style pass reports the syntax error separately)."""
    try:
        return {
            tok.start[0]: tok.string
            for tok in tokenize.generate_tokens(io.StringIO(src).readline)
            if tok.type == tokenize.COMMENT
        }
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return {i: ln for i, ln in enumerate(src.splitlines(), 1)}


@dataclass
class Finding:
    path: str
    line: int
    rule: str
    message: str
    suppressed: bool = False
    reason: str = ""

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


@dataclass
class Suppressions:
    """Per-file suppression map: kind -> {line -> reason}."""

    jax: Dict[int, str] = field(default_factory=dict)
    unlocked: Dict[int, str] = field(default_factory=dict)
    upload: Dict[int, str] = field(default_factory=dict)
    transfer: Dict[int, str] = field(default_factory=dict)
    donate: Dict[int, str] = field(default_factory=dict)
    problems: list = field(default_factory=list)


def parse_suppressions(src: str, path: str = "<src>") -> Suppressions:
    """A suppression applies to its own line; a suppression on a
    comment-only line (possibly the tail of a multi-line comment
    block) additionally covers the next CODE line — so reasons too
    long for an inline comment go in a block right above the site."""
    lines = src.splitlines()
    comments = _comment_lines(src)
    sup = Suppressions()
    for i, line in enumerate(lines, 1):
        m = SUPPRESSION_RE.search(comments.get(i, ""))
        if m is None:
            continue
        kind, reason = m.group(1), m.group(2).strip()
        if not reason:
            sup.problems.append(Finding(
                path, i, "bare-suppression",
                f"'# {kind}:' needs a reason (the annotation IS the "
                f"documentation)",
            ))
            continue
        target = {"jax-ok": sup.jax, "unlocked": sup.unlocked,
                  "upload-ok": sup.upload, "transfer-ok": sup.transfer,
                  "donate-ok": sup.donate}[kind]
        target[i] = reason
        if line.lstrip().startswith("#"):
            j = i  # 0-based index of the line AFTER the comment
            while j < len(lines) and lines[j].lstrip()[:1] in ("#", ""):
                j += 1
            if j < len(lines):
                target[j + 1] = reason
    return sup


def iter_source_files(
    repo: Path, roots: Iterable[str]
) -> Iterator[Tuple[str, Path]]:
    """Yield (repo-relative path, absolute path) of every .py file under
    the given roots, sorted, __pycache__ excluded."""
    for root in roots:
        p = repo / root
        if p.is_file():
            yield root, p
            continue
        for f in sorted(p.rglob("*.py")):
            if "__pycache__" in f.parts:
                continue
            yield str(f.relative_to(repo)), f
