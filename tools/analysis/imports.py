"""Base style pass: the original tools/lint.py checks, refactored.

Checks, per file: the file parses (SyntaxError == fail), unused imports,
bare ``except:``, tab indentation / trailing whitespace, mutable default
arguments, ``== True/False/None`` comparisons.

ImportCollector gap fixes over the original (ISSUE 5 satellite):

* names used only inside STRING annotations (``def f(x: "KVStore")`` —
  with ``from __future__ import annotations`` every forward reference
  is one) are now counted as uses: string constants in annotation
  position are parsed as expressions and their names collected;
* ``__all__`` re-exports declared as tuples, via ``__all__ += [...]``
  augmented assignment, or through an annotated assignment
  (``__all__: tuple = (...)``) are all honored (the original only read
  a plain ``__all__ = [...]`` and only caught ValueError, so a tuple
  containing a non-literal silently dropped the whole export list);
* dotted ``import a.b.c as d`` aliases bind ``d`` (the original split
  on "." and recorded ``a`` for the asname too).
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import List


class ImportCollector(ast.NodeVisitor):
    def __init__(self):
        self.imports: dict = {}   # bound name -> lineno
        self.used: set = set()
        self.exported: set = set()

    def visit_Import(self, node):
        for a in node.names:
            # `import a.b` binds `a`; `import a.b as c` binds `c`
            name = a.asname if a.asname else a.name.split(".")[0]
            self.imports[name] = node.lineno

    def visit_ImportFrom(self, node):
        if node.module == "__future__":
            return
        for a in node.names:
            if a.name == "*":
                continue
            self.imports[a.asname or a.name] = node.lineno

    def visit_Name(self, node):
        self.used.add(node.id)

    def _collect_annotation(self, ann) -> None:
        """Names in an annotation expression, including names inside
        string annotations (forward references / postponed evaluation)."""
        if ann is None:
            return
        for sub in ast.walk(ann):
            if isinstance(sub, ast.Name):
                self.used.add(sub.id)
            elif isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                try:
                    parsed = ast.parse(sub.value, mode="eval")
                except SyntaxError:
                    continue
                for n in ast.walk(parsed):
                    if isinstance(n, ast.Name):
                        self.used.add(n.id)

    def visit_arg(self, node):
        self._collect_annotation(node.annotation)
        self.generic_visit(node)

    def _visit_function(self, node):
        self._collect_annotation(node.returns)
        self.generic_visit(node)

    visit_FunctionDef = _visit_function
    visit_AsyncFunctionDef = _visit_function

    def _record_exports(self, value) -> None:
        try:
            names = ast.literal_eval(value)
        except (ValueError, SyntaxError, TypeError):
            return
        if isinstance(names, (list, tuple, set)):
            self.exported |= {n for n in names if isinstance(n, str)}

    def visit_Assign(self, node):
        for t in node.targets:
            if isinstance(t, ast.Name) and t.id == "__all__":
                self._record_exports(node.value)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        if isinstance(node.target, ast.Name) and node.target.id == "__all__":
            self._record_exports(node.value)
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        if (isinstance(node.target, ast.Name) and node.target.id == "__all__"
                and node.value is not None):
            self._record_exports(node.value)
        self._collect_annotation(node.annotation)
        self.generic_visit(node)


def style_problems(path: Path, src: str = None) -> List[str]:
    """The base per-file checks; returns formatted problem strings
    (kept string-typed — these predate Finding and feed `make lint`)."""
    problems = []
    if src is None:
        src = path.read_text()
    try:
        tree = ast.parse(src, filename=str(path))
    except SyntaxError as e:
        return [f"{path}:{e.lineno}: syntax error: {e.msg}"]

    lines = src.splitlines()
    noqa = {i + 1 for i, ln in enumerate(lines) if "# noqa" in ln}

    for i, ln in enumerate(lines, 1):
        if ln.rstrip() != ln and ln.strip():
            problems.append(f"{path}:{i}: trailing whitespace")
        if ln.startswith("\t"):
            problems.append(f"{path}:{i}: tab indentation")

    col = ImportCollector()
    col.visit(tree)
    # exemptions: used as a Name anywhere (annotations included), re-
    # exported via __all__, `# noqa` on the import line, or a leading-
    # underscore alias
    for name, lineno in col.imports.items():
        if name in col.used or name in col.exported or lineno in noqa:
            continue
        if name.startswith("_"):
            continue
        problems.append(f"{path}:{lineno}: unused import '{name}'")

    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            if node.lineno not in noqa:
                problems.append(f"{path}:{node.lineno}: bare 'except:'")
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for d in node.args.defaults + node.args.kw_defaults:
                if isinstance(d, (ast.List, ast.Dict, ast.Set)):
                    problems.append(
                        f"{path}:{node.lineno}: mutable default argument "
                        f"in '{node.name}'"
                    )
        if isinstance(node, ast.Compare):
            for cmp_op, val in zip(node.ops, node.comparators):
                if isinstance(cmp_op, (ast.Eq, ast.NotEq)) and \
                        isinstance(val, ast.Constant) and \
                        any(val.value is c for c in (True, False, None)):
                    if node.lineno not in noqa:
                        problems.append(
                            f"{path}:{node.lineno}: comparison to "
                            f"{val.value!r} — use 'is'/'is not'/truthiness"
                        )
    return problems
