"""The jit-registry manifest: every jitted entry point of the traced
roots (vpp_tpu/ops, vpp_tpu/pipeline, vpp_tpu/parallel), ENUMERATED —
the jax pass (tools/analysis/jaxlint.py) fails on any ``jax.jit`` call
site not registered here, and on any entry here that no longer matches
a call site (stale manifest). Adding a jit is a reviewed decision: it
changes what recompiles when, so it lands with a reason string.

Keys are ``(repo-relative path, enclosing scope qualname)``; the scope
is ``<module>`` for module-level calls, ``@name`` for a decorator on
``name``, else the dotted qualname of the enclosing function/method.

``TRACED_ROOTS`` additionally names functions that are traced INTO a
jit program but whose wrapping is indirect (the first argument of the
``jax.jit`` call is an expression the AST pass cannot resolve — e.g.
``jax.jit(_packed_call(fn))``). These are the roots the host-sync /
tracer-branch rules start their reachability closure from; a root that
names a function that no longer exists is a finding.
"""

# (relpath, scope) -> why this site exists / what caches it
JIT_SITES = {
    ("vpp_tpu/pipeline/dataplane.py", "_jitted_step"):
        "THE step factory: process-wide _JIT_STEPS cache keyed "
        "(impl, skip, fast, form, sweep_stride); compile counting "
        "wraps fn here",
    ("vpp_tpu/ops/session.py", "<module>"):
        "session_expire: the on-demand BULK session reclaim (tests, "
        "CLI, idle-node maintenance) — one fused program instead of a "
        "dozen eager whole-table ops at the 10M-slot regime; "
        "now/max_age are traced scalars so values never retrace. "
        "Steady-state aging is NOT this: session_sweep rides the "
        "fused pipeline step (graph._finish_step)",
    ("vpp_tpu/pipeline/dataplane.py", "Dataplane.encap_remote"):
        "lazy vxlan_encap jit; module-level target fn, built once per "
        "dataplane on first remote-disposed frame",
    ("vpp_tpu/pipeline/dataplane.py", "Dataplane.time_classifier"):
        "diagnostic classify probe; per-impl cache on the instance, "
        "bench/operator path — never hot",
    ("vpp_tpu/pipeline/graph.py", "<module>"):
        "pipeline_step_jit: the module-level reference step (tests, "
        "trace/cycles)",
    ("vpp_tpu/pipeline/tables.py", "_glb_update_fn"):
        "incremental glb-blob upload kernel; memoized per (w_r, w_c, "
        "planes) block geometry",
    ("vpp_tpu/pipeline/tables.py", "_fib_update_fn"):
        "incremental per-slot FIB blob scatter (ISSUE 15): a route "
        "flap at the 1M-route regime ships a few-KB blob instead of "
        "9 full columns; memoized per block width",
    ("vpp_tpu/pipeline/tables.py", "_svc_update_fn"):
        "incremental svc-plane blob scatter (ISSUE 19): a rolling "
        "backend replacement ships the changed VIP rows as one "
        "few-KB packed blob — zero ACL/ML/FIB bytes; memoized per "
        "(block width, backend ways)",
    ("vpp_tpu/parallel/cluster.py", "make_cluster_step"):
        "the SPMD cluster step (shard_map over the node mesh); built "
        "once per mesh by ClusterDataplane",
    ("vpp_tpu/ops/acl_mxu.py", "@mxu_first_match"):
        "pallas first-match kernel entry; static interpret flag only",
    ("vpp_tpu/ops/acl_bv.py", "@bv_first_set"):
        "pallas BV word-AND + first-set-bit kernel entry (ISSUE 16); "
        "static interpret flag only — the fused rung gathers segment "
        "rows on-device and reduces them in VMEM tiles",
    ("vpp_tpu/ops/lpm.py", "@lpm_fused_lookup"):
        "pallas LPM binary-search kernel entry (ISSUE 16): one grid "
        "fused over the populated length planes, longest-first "
        "first-hit-wins accumulation; static interpret flag only",
    ("vpp_tpu/ops/session.py", "@sess_probe_ways"):
        "pallas session bucket-probe kernel entry (ISSUE 16): whole "
        "key columns staged to VMEM, per-packet way election in-core; "
        "static interpret flag only",
    ("vpp_tpu/pipeline/snapshot.py", "_fetch_fn"):
        "bounded chunk drain for the crash-consistent session "
        "snapshot (ISSUE 8): one [C, CB, W] stacked fetch per chunk, "
        "lru_cache-memoized per chunk_buckets geometry; the start "
        "offset is a traced scalar so draining the ring never "
        "retraces",
    ("vpp_tpu/pipeline/snapshot.py", "_digest_fn"):
        "per-chunk content digest for incremental snapshots (ISSUE "
        "8): one on-device O(table) pass returning [n_chunks] uint32 "
        "— only chunks whose digest moved drain; memoized per "
        "chunk_buckets geometry",
    ("vpp_tpu/tenancy/derive.py", "<module>"):
        "tenant_occupancy: per-tenant live-session slice counts for "
        "`show tenants` / vpp_tpu_tenant_sess_occupancy (ISSUE 14) — "
        "one on-device prefix sum returning [T] ints, compiled once "
        "per table geometry; an observability path, never hot",
}

# (relpath, dotted def qualname) traced into jit programs indirectly
TRACED_ROOTS = {
    # the step factory composition: jax.jit(make_pipeline_step(...))
    ("vpp_tpu/pipeline/graph.py", "make_pipeline_step.step"),
    ("vpp_tpu/pipeline/graph.py", "pipeline_step"),
    ("vpp_tpu/pipeline/graph.py", "pipeline_step_fast"),
    ("vpp_tpu/pipeline/graph.py", "pipeline_step_auto"),
    # set-associative session table (ISSUE 6): the insert core and the
    # amortized in-step sweep are traced INTO every step variant via
    # graph.py; session_expire's impl is wrapped by the module-level
    # jit registered above; the linear-probe baseline is traced only by
    # bench.py's jitted old-vs-new shoot-out
    ("vpp_tpu/ops/session.py", "hashmap_insert"),
    ("vpp_tpu/ops/session.py", "session_sweep"),
    ("vpp_tpu/ops/session.py", "_session_expire_impl"),
    ("vpp_tpu/ops/session.py", "hashmap_insert_linear"),
    # the packed/chained IO boundary wrappers: jax.jit(_packed_call(fn))
    # — each has an off-signature and a telemetry-signature variant
    # (ISSUE 11) sharing one _core/_loop body
    ("vpp_tpu/pipeline/dataplane.py", "_packed_call._core"),
    ("vpp_tpu/pipeline/dataplane.py", "_packed_call.run"),
    ("vpp_tpu/pipeline/dataplane.py", "_chained_call.run_off"),
    ("vpp_tpu/pipeline/dataplane.py", "_chained_call.run_tel"),
    # the device-ring window program (ISSUE 7): jax.jit(_ring_call(fn,
    # slots)) through _jitted_step — the persistent pump's steady
    # state; the old per-instance PersistentPump.__init__ jit site is
    # GONE (the ring form rides the process-wide step cache, so an
    # epoch-swap pump restart recompiles nothing)
    ("vpp_tpu/pipeline/dataplane.py", "_ring_call.run"),
    ("vpp_tpu/pipeline/dataplane.py", "_ring_call.run_tel"),
    ("vpp_tpu/pipeline/dataplane.py", "_ring_call._loop"),
    # the per-packet ML stage (ISSUE 10): traced into every step
    # variant whose ml_mode gate is on via graph._ml_eval — the stage
    # rides the SAME process-wide _jitted_step cache (no jit site of
    # its own, so an ML-enabled step compiles once, never per epoch)
    ("vpp_tpu/ops/mlscore.py", "ml_features"),
    ("vpp_tpu/ops/mlscore.py", "ml_score"),
    ("vpp_tpu/ops/mlscore.py", "ml_policy"),
    ("vpp_tpu/ops/session.py", "session_hit_age"),
    # the device telemetry plane (ISSUE 11): the flow sketch rides
    # every "full"-gated step variant via graph._finish_step, the
    # latency histogram + rider ride the packed/chained/ring boundary
    # wrappers via dataplane._packed_call/_ring_call — all through the
    # SAME process-wide _jitted_step cache (no jit site of their own)
    ("vpp_tpu/ops/telemetry.py", "tel_flow_update"),
    ("vpp_tpu/ops/telemetry.py", "tel_flow_hash"),
    ("vpp_tpu/ops/telemetry.py", "tel_latency_update"),
    ("vpp_tpu/ops/telemetry.py", "lat_bucket"),
    ("vpp_tpu/ops/telemetry.py", "sketch_cols"),
    ("vpp_tpu/ops/telemetry.py", "pack_tel_rider"),
    # the LPM FIB + shared resolver (ISSUE 15): reached through the
    # step factory's _fib_fn indirection (the _classifier_fns twin),
    # so the reachability closure needs them named explicitly
    ("vpp_tpu/ops/lpm.py", "fib_lookup_lpm"),
    ("vpp_tpu/ops/lpm.py", "fib_lookup_lpm_fused"),
    ("vpp_tpu/ops/fib.py", "fib_lookup_dense"),
    ("vpp_tpu/ops/fib.py", "resolve_fib_slot"),
    ("vpp_tpu/ops/fib.py", "fib_flow_mix"),
    ("vpp_tpu/ops/fib.py", "ip4_lookup"),
    # classifier implementations reach jit through _classifier_fns /
    # time_classifier's subscripted call — enumerate them explicitly
    ("vpp_tpu/ops/acl.py", "acl_classify_global"),
    ("vpp_tpu/ops/acl.py", "acl_classify_local"),
    ("vpp_tpu/ops/acl.py", "acl_local_none"),
    ("vpp_tpu/ops/acl_mxu.py", "acl_classify_global_mxu"),
    ("vpp_tpu/ops/acl_bv.py", "acl_classify_global_bv"),
    ("vpp_tpu/ops/acl_bv.py", "acl_classify_local_bv"),
    ("vpp_tpu/ops/acl_bv.py", "acl_classify_global_pallas"),
    ("vpp_tpu/ops/acl_bv.py", "acl_classify_local_pallas"),
    # mesh-sharded classify substitutions (parallel/cluster.py body)
    ("vpp_tpu/parallel/cluster.py", "sharded_global_classify"),
    ("vpp_tpu/parallel/cluster.py", "sharded_global_classify_mxu"),
    # vxlan encap rides its own jit (Dataplane.encap_remote) AND the
    # overlay-gated step forms (ISSUE 19: graph._finish_step builds
    # the outer header in-step); decap + the VNI→tenant map are traced
    # into the same overlay step forms via the decap stage ahead of
    # ip4-input — all through the ONE _jitted_step cache dimension
    ("vpp_tpu/ops/vxlan.py", "vxlan_encap"),
    ("vpp_tpu/ops/vxlan.py", "vxlan_decap_step"),
    ("vpp_tpu/tenancy/derive.py", "vni_tenant"),
    # the svc DNAT consult (ISSUE 19) rides every step variant via
    # ops/nat44.nat44_dnat (inert one-row gather when svc_vips == 0)
    ("vpp_tpu/ops/nat44.py", "_svc_lookup"),
    # the tenant stage (ISSUE 14): derivation + token bucket +
    # accounting are traced into every tenancy-gated step variant via
    # graph._tenant_eval/_finish_step, and the tenant-sliced bucket
    # computation into the session/NAT ops — all through the SAME
    # process-wide _jitted_step cache (exactly one new step form)
    ("vpp_tpu/tenancy/derive.py", "addr_tenant"),
    ("vpp_tpu/tenancy/derive.py", "key_tenant"),
    ("vpp_tpu/tenancy/derive.py", "tenant_ids"),
    ("vpp_tpu/tenancy/derive.py", "tenant_limit"),
    ("vpp_tpu/tenancy/derive.py", "tnt_account"),
    ("vpp_tpu/tenancy/derive.py", "_tenant_occupancy_impl"),
    ("vpp_tpu/ops/session.py", "tenant_bucket"),
}

# --- the Pallas kernel registry (ISSUE 16) ---------------------------
# Every ``pl.pallas_call`` entry point in the tree, ENUMERATED with the
# DataplaneTables fields its operands are built from and the ladder
# knob that selects it. The --partitions lint
# (tools/analysis/registries.py) walks this: each entry must import,
# each named field must resolve in the partition spec, and the knob
# must be REJECTED by validate_partitioning on a rule-sharded mesh
# until a PARTITION_RULES spec covers the fused kernel — a pallas rung
# must never fail inside pallas_call at trace time.
#
# (relpath, jit-entry scope) -> {"fn": dispatch-root qualname,
#                                "knob": config knob that selects it,
#                                "fields": DataplaneTables operands}
PALLAS_KERNELS = {
    ("vpp_tpu/ops/acl_mxu.py", "@mxu_first_match"): {
        "fn": "acl_classify_global_mxu",
        "knob": "classifier",
        "fields": ("glb_mxu_coeff", "glb_mxu_k", "glb_mxu_act"),
    },
    ("vpp_tpu/ops/acl_bv.py", "@bv_first_set"): {
        "fn": "acl_classify_global_pallas",
        "knob": "classifier",
        "fields": (
            "glb_bv_bnd_src", "glb_bv_bnd_dst", "glb_bv_bnd_sport",
            "glb_bv_bnd_dport", "glb_bv_nbnd", "glb_bv_src",
            "glb_bv_dst", "glb_bv_sport", "glb_bv_dport",
            "glb_bv_proto",
            "acl_bv_bnd_src", "acl_bv_bnd_dst", "acl_bv_bnd_sport",
            "acl_bv_bnd_dport", "acl_bv_nbnd", "acl_bv_src",
            "acl_bv_dst", "acl_bv_sport", "acl_bv_dport",
            "acl_bv_proto",
        ),
    },
    ("vpp_tpu/ops/lpm.py", "@lpm_fused_lookup"): {
        "fn": "fib_lookup_lpm_fused",
        "knob": "fib_impl",
        "fields": tuple(f"fib_lpm_p{i}" for i in range(33))
        + ("fib_lpm_cnt",),
    },
    ("vpp_tpu/ops/session.py", "@sess_probe_ways"): {
        "fn": "_sess_probe_dispatch",
        "knob": "session_impl",
        "fields": ("sess_valid", "sess_src", "sess_dst", "sess_ports",
                   "sess_proto", "sess_time"),
    },
}


# --- donation registry (ISSUE 20, the --donate pass) ----------------------
#
# Every jax.jit call with a NON-EMPTY donate_argnums must be registered
# here by (relpath, enclosing scope): donation is an ownership transfer,
# and an unregistered donating jit is a use-after-donate bug waiting for
# a reader (the PR-8 checkpoint_sessions hazard).  The reason documents
# who owns the buffers and why donating is safe.
DONATED_JIT_SITES = {
    ("vpp_tpu/pipeline/dataplane.py", "_jitted_step"): (
        "the packed/ring/chain step factories: packed+chain donate only "
        "the flat input column block (a fresh jnp.asarray temp at every "
        "call site); ring donates the tables carry + cursor + rx window, "
        "owned by the persistent pump which threads the returned carry"),
    ("bench.py", "sub_benches"): (
        "throughput loop donates its private dataplane's tables; the "
        "carry is rebound from StepResult every iteration"),
    ("bench.py", "session_scale_bench"): (
        "hashmap shoot-out donates the pristine() column sets (rebuilt "
        "per window) and the 10M-resident insert carry (rebound from "
        "the result tuple)"),
    ("bench.py", "_run"): (
        "headline loop donates its private dataplane's tables; carry "
        "rebound from StepResult; commit_bench runs on its OWN "
        "dataplane for exactly this reason (its docstring)"),
}

# Donating CALL sites the use-after-donate dataflow checks:
# (relpath, enclosing scope, callee expression) -> (argnums, reason).
# The pass finds every matching call in that scope, tracks the donated
# name arguments, and flags any read that can observe the invalidated
# buffer (straight-line reads after the call, and loop-carried reads
# with no rebind in between).  Donated values may only be re-exposed
# via the sanctioned copy points (checkpoint_sessions / _serve_ckpt
# jnp.copy, the stager hand-off) — those live in OTHER scopes and get
# a fresh reference, never the donated one.
DONATING_CALLS = {
    ("vpp_tpu/pipeline/persistent.py", "PersistentPump._stage_loop",
     "self._step"): (
        (0, 1, 2),
        "ring window program: donates tables carry + cursor + rx "
        "window; _stage_loop rebinds all three from the result tuple "
        "in the same statement"),
    ("vpp_tpu/pipeline/dataplane.py", "Dataplane.process_packed",
     "step"): (
        (1,),
        "packed column block: the donated arg is a fresh "
        "jnp.asarray(flat) temp, never a named value"),
    ("vpp_tpu/pipeline/dataplane.py",
     "Dataplane.process_packed_chain", "step"): (
        (1,),
        "chained packed block: same fresh-temp discipline as "
        "process_packed"),
    ("bench.py", "measure_mpps", "step"): (
        (0,),
        "tables carry donated and rebound from res.tables each "
        "iteration"),
    ("bench.py", "session_scale_bench", "fn"): (
        (0, 1, 2, 3, 4, 5),
        "the six hashmap columns are rebuilt by pristine() before "
        "every donating call"),
    ("bench.py", "session_scale_bench", "insert"): (
        (0,),
        "10M-resident carry: rebound from the result tuple in the "
        "same statement"),
    ("bench.py", "_run", "step"): (
        (0,),
        "headline tables carry: rebound from res.tables each "
        "iteration"),
}
