#!/usr/bin/env python
"""In-tree analysis CLI (the metalinter CI stage analog of the
reference — README.md:36-40, docker/development Dockerfile.metalinter —
grown from a single-file linter into the tools/analysis/ package of
project-specific passes; ISSUE 5 tentpole).

Always runs the base style pass (parse, unused imports, bare except,
tabs/trailing whitespace, mutable defaults) over ROOTS. Flags add:

  --jax       tracer/recompile hygiene over vpp_tpu/{ops,pipeline,
              parallel}: host syncs inside traced code, Python control
              flow on tracers, per-instance jit closures (the PR-4 bug
              class), float64 drift, and the jit-registry manifest
              check (every jax.jit site enumerated in
              tools/analysis/jit_manifest.py). Suppress one line with
              `# jax-ok: <reason>`.
  --threads   lock discipline over io/pump.py, io/cluster_pump.py,
              kvstore/, stats/, trace/, pipeline/txn.py: attributes
              written under `with self._lock` must be accessed under
              it everywhere (`__init__` and `*_locked` methods
              exempt), and lock-nesting order must be acyclic.
              Suppress one line with `# unlocked: <reason>`.
  --metrics   Prometheus registry hygiene (imports jax; tier-1 runs it
              via tests/test_exposition.py).
  --counters  StepStats <-> Prometheus family parity (imports jax).
  --tables    BV classifier table invariants (imports jax; tier-1 runs
              it via tests/test_acl_bv.py).
  --partitions partition-rule completeness (ISSUE 12): every
              DataplaneTables field resolves to an explicit
              vpp_tpu/parallel/partition.py rule (sharded or
              replicated-by-design), no stale rules. Tier-1 runs it
              via tests/test_partition.py; `make lint` includes it.
  --uploads   upload-group consistency over pipeline/tables.py and
              its callers (ISSUE 20): every DataplaneTables field
              placed in exactly one _UPLOAD_GROUPS entry or state
              ledger (manifest: tools/analysis/upload_manifest.py),
              and every TableBuilder staged-attr write marks its
              group dirty on every path. Suppress one line with
              `# upload-ok: <reason>`.
  --transfers host materialization of table-scale device values
              (np.asarray / jax.device_get / .item() / int() on
              DataplaneTables-reachable values) outside the approved
              fetch sites (tools/analysis/transfer_manifest.py).
              Suppress with `# transfer-ok: <reason>`.
  --donate    use-after-donate over the registered donating jit call
              sites (jit_manifest.DONATING_CALLS), plus unregistered
              non-empty donate_argnums detection. Suppress with
              `# donate-ok: <reason>`.

Exit code 1 if anything fires. `make lint` runs the base + --jax +
--threads + --uploads + --transfers + --donate (the pure-AST passes).
Rule catalog + suppression syntax: docs/STATIC_ANALYSIS.md.
"""

from __future__ import annotations

import sys
from pathlib import Path

_TOOLS = Path(__file__).resolve().parent
if str(_TOOLS) not in sys.path:  # lint.py is loaded by path from tests
    sys.path.insert(0, str(_TOOLS))

from analysis.imports import style_problems  # noqa: E402
from analysis.jaxlint import jax_lint  # noqa: E402
from analysis.registries import (  # noqa: E402  (re-exported: tier-1
    counters_lint,                 # loads lint.py by path and calls
    metrics_lint,                  # these directly)
    partitions_lint,
    tables_lint,
)
from analysis.threadlint import threads_lint  # noqa: E402
from analysis.uploadlint import uploads_lint  # noqa: E402
from analysis.transferlint import transfers_lint  # noqa: E402
from analysis.donatelint import donate_lint  # noqa: E402

ROOTS = ("vpp_tpu", "tests", "bench.py", "__graft_entry__.py", "tools")


def lint_file(path: Path) -> list:
    """Base style pass on one file (kept as the public per-file API)."""
    return style_problems(path)


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    repo = Path(__file__).resolve().parent.parent
    files = []
    for root in ROOTS:
        p = repo / root
        if p.is_file():
            files.append(p)
        else:
            files.extend(sorted(p.rglob("*.py")))
    all_problems = []
    for f in files:
        if "__pycache__" in str(f):
            continue
        all_problems.extend(lint_file(f))
    if "--jax" in argv:
        all_problems.extend(str(f) for f in jax_lint(repo))
    if "--threads" in argv:
        all_problems.extend(str(f) for f in threads_lint(repo))
    if "--uploads" in argv:
        all_problems.extend(str(f) for f in uploads_lint(repo))
    if "--transfers" in argv:
        all_problems.extend(str(f) for f in transfers_lint(repo))
    if "--donate" in argv:
        all_problems.extend(str(f) for f in donate_lint(repo))
    if "--metrics" in argv:
        all_problems.extend(metrics_lint())
    if "--counters" in argv:
        all_problems.extend(counters_lint())
    if "--tables" in argv:
        all_problems.extend(tables_lint())
    if "--partitions" in argv:
        all_problems.extend(partitions_lint())
    # --jax and --threads both report bare suppressions; dedupe
    seen, unique = set(), []
    for p in all_problems:
        if str(p) not in seen:
            seen.add(str(p))
            unique.append(p)
    for p in unique:
        print(p)
    print(f"lint: {len(files)} files, {len(unique)} problems")
    return 1 if unique else 0


if __name__ == "__main__":
    sys.exit(main())
