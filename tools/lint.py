#!/usr/bin/env python
"""Minimal in-tree linter (the `go fmt`/`golint` analog of the
reference's CI — README.md:36-40, docker/development Dockerfile.metalinter
— rebuilt for a no-external-deps environment).

Checks, per file:
  * the file parses (SyntaxError == fail)
  * unused imports (module scope; names re-exported via __all__ or
    marked `# noqa: unused` are exempt)
  * `except:` bare except clauses
  * tabs in indentation and trailing whitespace
  * mutable default arguments (def f(x=[]) / {} / set())

Exit code 1 if anything fires. Run via `make lint`.

`--metrics` additionally runs the metrics lint: it builds the standard
Prometheus registries (agent stats collector + control-plane
histograms, KSR gauges, kvstore request histogram) and validates every
registered family — name matches ``vpp_tpu_[a-z0-9_]+``, non-empty
help, no duplicate family names across paths. Importing the dataplane
pulls jax, so this pass only runs when asked for (tier-1:
tests/test_exposition.py invokes it).

`--counters` runs the counter-parity pass: every pipeline StepStats
field must map (via stats/collector.py STEPSTATS_FAMILIES) to a
registered Prometheus family, and every registered
``vpp_tpu_pipeline_*`` family must map back to a StepStats field —
so a counter added in the kernel without its observability twin (or
vice versa) fails tier-1 alongside --metrics.

`--tables` runs the table-structure invariant pass over a
representative BV-classifier commit (ops/acl_bv.py): interval
boundaries strictly sorted per dimension, bitmap word width matching
the padded rule capacity, padding provably inert (no bit of a rule
row >= nrules set anywhere, interval rows past the live boundary
count all-zero), and the BV/dense/MXU capacity constants consistent.
Invoked from tier-1 (tests/test_acl_bv.py).
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

ROOTS = ("vpp_tpu", "tests", "bench.py", "__graft_entry__.py", "tools")


class ImportCollector(ast.NodeVisitor):
    def __init__(self):
        self.imports: dict = {}   # name -> (lineno, stmt text)
        self.used: set = set()
        self.exported: set = set()

    def visit_Import(self, node):
        for a in node.names:
            name = (a.asname or a.name).split(".")[0]
            self.imports[name] = node.lineno

    def visit_ImportFrom(self, node):
        if node.module == "__future__":
            return
        for a in node.names:
            if a.name == "*":
                continue
            self.imports[a.asname or a.name] = node.lineno

    def visit_Name(self, node):
        self.used.add(node.id)

    def visit_Attribute(self, node):
        self.generic_visit(node)

    def visit_Assign(self, node):
        # __all__ = [...] re-exports
        for t in node.targets:
            if isinstance(t, ast.Name) and t.id == "__all__":
                try:
                    self.exported |= set(ast.literal_eval(node.value))
                except ValueError:
                    pass
        self.generic_visit(node)


def lint_file(path: Path) -> list:
    problems = []
    src = path.read_text()
    try:
        tree = ast.parse(src, filename=str(path))
    except SyntaxError as e:
        return [f"{path}:{e.lineno}: syntax error: {e.msg}"]

    lines = src.splitlines()
    noqa = {i + 1 for i, ln in enumerate(lines) if "# noqa" in ln}

    for i, ln in enumerate(lines, 1):
        if ln.rstrip() != ln and ln.strip():
            problems.append(f"{path}:{i}: trailing whitespace")
        if ln.startswith("\t"):
            problems.append(f"{path}:{i}: tab indentation")

    col = ImportCollector()
    col.visit(tree)
    # exemptions: used as a Name anywhere, re-exported via __all__,
    # `# noqa` on the import line, or a leading-underscore alias
    for name, lineno in col.imports.items():
        if name in col.used or name in col.exported or lineno in noqa:
            continue
        if name.startswith("_"):
            continue
        problems.append(f"{path}:{lineno}: unused import '{name}'")

    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            if node.lineno not in noqa:
                problems.append(f"{path}:{node.lineno}: bare 'except:'")
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for d in node.args.defaults + node.args.kw_defaults:
                if isinstance(d, (ast.List, ast.Dict, ast.Set)):
                    problems.append(
                        f"{path}:{node.lineno}: mutable default argument "
                        f"in '{node.name}'"
                    )
        if isinstance(node, ast.Compare):
            for cmp_op, val in zip(node.ops, node.comparators):
                if isinstance(cmp_op, (ast.Eq, ast.NotEq)) and \
                        isinstance(val, ast.Constant) and \
                        any(val.value is c for c in (True, False, None)):
                    if node.lineno not in noqa:
                        problems.append(
                            f"{path}:{node.lineno}: comparison to "
                            f"{val.value!r} — use 'is'/'is not'/truthiness"
                        )
    return problems


def _build_full_registry():
    """Every family the deployed processes serve, in ONE registry (so
    cross-path duplicates are caught). Shared by the --metrics and
    --counters passes."""
    repo = str(Path(__file__).resolve().parent.parent)
    if repo not in sys.path:  # direct `python tools/lint.py` invocation
        sys.path.insert(0, repo)
    from vpp_tpu.ksr.reflector import ReflectorRegistry
    from vpp_tpu.kvstore.server import make_request_histogram
    from vpp_tpu.pipeline.dataplane import Dataplane
    from vpp_tpu.pipeline.tables import DataplaneConfig
    from vpp_tpu.stats.collector import (
        StatsCollector,
        register_control_plane_metrics,
        register_ksr_gauges,
    )

    dp = Dataplane(DataplaneConfig(
        max_tables=2, max_rules=8, max_global_rules=8, max_ifaces=8,
        fib_slots=16, sess_slots=64, nat_mappings=2, nat_backends=4))
    coll = StatsCollector(dp)
    register_control_plane_metrics(coll.registry)
    # the KSR and kvserver families live on other processes/paths; fold
    # them into the same registry so cross-path duplicates are caught
    register_ksr_gauges(coll.registry, ReflectorRegistry(), path="/metrics")
    coll.registry.register("/kvstore", make_request_histogram())
    return coll.registry


def metrics_lint() -> list:
    """Build every registry the deployed processes serve and validate
    the registered families (MetricsRegistry.lint). Returns problems."""
    return _build_full_registry().lint()


def counters_lint() -> list:
    """Counter-parity pass: every StepStats field must map to a
    registered Prometheus family (stats/collector.py
    STEPSTATS_FAMILIES), and every registered ``vpp_tpu_pipeline_*``
    family must map back to a StepStats field — a pipeline counter
    added on either side without its observability twin fails here
    (and tier-1, via tests/test_exposition.py)."""
    registry = _build_full_registry()
    from vpp_tpu.pipeline.graph import StepStats
    from vpp_tpu.stats.collector import STEPSTATS_FAMILIES

    problems = []
    fields = set(StepStats._fields)
    mapped = set(STEPSTATS_FAMILIES)
    for f in sorted(fields - mapped):
        problems.append(
            f"counters: StepStats.{f} has no Prometheus family mapping "
            f"(stats/collector.py STEPSTATS_FAMILIES)"
        )
    for f in sorted(mapped - fields):
        problems.append(
            f"counters: STEPSTATS_FAMILIES maps {f!r} which is not a "
            f"StepStats field (stale entry?)"
        )
    registered = {fam.name for _path, fam in registry.families()}
    for f, family in sorted(STEPSTATS_FAMILIES.items()):
        if family not in registered:
            problems.append(
                f"counters: StepStats.{f} maps to unregistered family "
                f"{family!r}"
            )
    mapped_families = set(STEPSTATS_FAMILIES.values())
    for name in sorted(registered):
        if name.startswith("vpp_tpu_pipeline_") and \
                name not in mapped_families:
            problems.append(
                f"counters: family {name!r} is in the pipeline "
                f"namespace but maps to no StepStats field"
            )
    return problems


def _bv_plane_problems(name: str, bv, nrules: int, max_rules: int) -> list:
    """Invariants of ONE compiled BvTable against its live rule count."""
    import numpy as np

    from vpp_tpu.ops.acl_bv import DIMS, bv_capacity

    problems = []
    cap_i, cap_w, cap_pr = bv_capacity(max_rules, True)
    planes = {dim: getattr(bv, f"bm_{dim}") for dim in DIMS}
    planes["proto"] = bv.bm_proto
    for k, dim in enumerate(DIMS):
        bnd = getattr(bv, f"bnd_{dim}")
        n = int(bv.nbnd[k])
        if len(bnd) != cap_i:
            problems.append(
                f"tables: {name}.{dim} boundary capacity {len(bnd)} != "
                f"bv_capacity {cap_i}")
        live = bnd[:n].astype(np.int64)
        if n and not (np.diff(live) > 0).all():
            problems.append(
                f"tables: {name}.{dim} boundaries not strictly sorted")
        if n and live[0] != 0:
            problems.append(
                f"tables: {name}.{dim} boundary[0] != 0 (value space "
                f"must be fully covered)")
    for pname, bm in planes.items():
        if bm.shape[-1] != cap_w or cap_w != max(1, (max_rules + 31) // 32):
            problems.append(
                f"tables: {name}.{pname} word width {bm.shape[-1]} does "
                f"not match padded rule capacity {max_rules}")
        # padding inert, rule axis: no bit of a row >= nrules anywhere
        for w in range(bm.shape[-1]):
            lo_rule = w * 32
            nbits = min(32, max(0, nrules - lo_rule))
            allowed = np.uint32((1 << nbits) - 1)
            if (bm[..., w] & ~allowed).any():
                problems.append(
                    f"tables: {name}.{pname} word {w} sets bits of "
                    f"padding rules (nrules={nrules})")
        # padding inert, interval axis: rows past the live boundary
        # count must be all-zero (a clipped lookup can never land
        # there; a stale bit would be a silent wrong-match hazard)
        if pname != "proto":
            n = int(bv.nbnd[list(DIMS).index(pname)])
            if bm[n:].any():
                problems.append(
                    f"tables: {name}.{pname} has bits set in interval "
                    f"rows >= nbnd ({n})")
    return problems


def tables_lint() -> list:
    """Table-structure invariant pass (`--tables`): commit a
    representative rule set through a BV-enabled TableBuilder and
    validate the compiled structure + the cross-implementation
    capacity constants. Returns problems."""
    repo = str(Path(__file__).resolve().parent.parent)
    if repo not in sys.path:
        sys.path.insert(0, repo)
    import ipaddress

    from vpp_tpu.ir.rule import Action, ContivRule, Protocol
    from vpp_tpu.ops.acl_bv import bv_capacity, bv_global_bytes
    from vpp_tpu.ops.acl_mxu import mxu_rule_capacity
    from vpp_tpu.pipeline.tables import DataplaneConfig, TableBuilder

    cfg = DataplaneConfig(
        max_tables=2, max_rules=16, max_global_rules=96, max_ifaces=8,
        fib_slots=16, sess_slots=64, nat_mappings=2, nat_backends=4,
        classifier="bv")
    b = TableBuilder(cfg)
    rules = [
        ContivRule(action=Action.PERMIT, protocol=Protocol.TCP,
                   src_network=ipaddress.ip_network(f"10.{i}.0.0/16"),
                   dest_port=80 + i)
        for i in range(40)
    ] + [
        ContivRule(action=Action.DENY, protocol=Protocol.UDP,
                   dest_port=0),
        ContivRule(action=Action.PERMIT),        # wildcard everything
        ContivRule(action=Action.DENY, protocol=Protocol.TCP,
                   dest_port=65535),
        ContivRule(action=Action.DENY),          # terminal deny-all
    ]
    b.set_global_table(rules)
    b.set_local_table(0, rules[:7])
    # slot 1 stays empty: its planes must be entirely inert

    problems = _bv_plane_problems("glb", b.glb_bv, b.glb_nrules,
                                  cfg.max_global_rules)
    for slot, nrules in ((0, 7), (1, 0)):
        from vpp_tpu.ops.acl_bv import BvTable

        local = BvTable(
            bnd_src=b.acl_bv["bnd_src"][slot],
            bnd_dst=b.acl_bv["bnd_dst"][slot],
            bnd_sport=b.acl_bv["bnd_sport"][slot],
            bnd_dport=b.acl_bv["bnd_dport"][slot],
            nbnd=b.acl_bv["nbnd"][slot],
            bm_src=b.acl_bv["src"][slot], bm_dst=b.acl_bv["dst"][slot],
            bm_sport=b.acl_bv["sport"][slot],
            bm_dport=b.acl_bv["dport"][slot],
            bm_proto=b.acl_bv["proto"][slot],
            ok=bool(b.acl_bv_ok[slot]), build_ms=0.0,
        )
        problems += _bv_plane_problems(f"local[{slot}]", local, nrules,
                                       cfg.max_rules)
    # cross-implementation capacity constants
    for r in (cfg.max_rules, cfg.max_global_rules, 1024, 10240):
        ib, w, _pr = bv_capacity(r, True)
        if ib != 2 * r + 2:
            problems.append(
                f"tables: bv interval capacity {ib} != 2*{r}+2")
        if w * 32 < r:
            problems.append(
                f"tables: bv word capacity {w}*32 < {r} rules")
        if mxu_rule_capacity(r) < r:
            problems.append(
                f"tables: mxu rule capacity {mxu_rule_capacity(r)} < {r}")
        if bv_global_bytes(r) < ib * w * 4 * 4:
            problems.append(
                f"tables: bv_global_bytes({r}) smaller than its own "
                f"bitmap matrices")
    return problems


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    repo = Path(__file__).resolve().parent.parent
    files = []
    for root in ROOTS:
        p = repo / root
        if p.is_file():
            files.append(p)
        else:
            files.extend(sorted(p.rglob("*.py")))
    all_problems = []
    for f in files:
        if "__pycache__" in str(f):
            continue
        all_problems.extend(lint_file(f))
    if "--metrics" in argv:
        all_problems.extend(metrics_lint())
    if "--counters" in argv:
        all_problems.extend(counters_lint())
    if "--tables" in argv:
        all_problems.extend(tables_lint())
    for p in all_problems:
        print(p)
    print(f"lint: {len(files)} files, {len(all_problems)} problems")
    return 1 if all_problems else 0


if __name__ == "__main__":
    sys.exit(main())
