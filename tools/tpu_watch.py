#!/usr/bin/env python
"""TPU recovery watcher: capture a full bench when the axon tunnel is up.

The tunnel comes and goes (down ~19 h on 2026-07-30; a brief window on
2026-07-31 03:46 closed again within ~25 min, wedging a bench mid-run).
This watcher loops forever:

  1. probe the tunnel in a throwaway subprocess (tiny matmul EXECUTED,
     not just jax.devices() — a half-wedged tunnel answers enumeration
     and then hangs the first real RPC)
  2. on success, export a git-archive snapshot of the repo's committed
     HEAD (ADVICE r3 item 5: captures must be reproducible from a
     commit, not a drifting working tree) and run bench.py there with
     --progress-out so every finished section survives a mid-run wedge
  3. a watchdog kills the bench if it exceeds its deadline (a wedged
     RPC blocks forever otherwise); whatever the sidecar holds is kept
  4. a COMPLETE run writes BENCH_r04_manual_tpu.json (+ git commit);
     a partial run writes/refreshes BENCH_r04_partial_tpu.json iff it
     got further than any earlier attempt

Run detached:  nohup python tools/tpu_watch.py >/tmp/tpu_watch_r05.log 2>&1 &
(The target round defaults to 05; override with VPPT_BENCH_ROUND=rNN.)
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PROBE_TIMEOUT_S = 90
# a killed-mid-claim probe is itself the wedge trigger (the grant needs
# ~3-10 min UNPOKED to recover) — the gap between killed probes must
# exceed the recovery window's high end, or the watcher itself keeps
# the grant wedged forever
PROBE_INTERVAL_S = 600
BENCH_DEADLINE_S = 2700  # 45 min; a healthy-tunnel full run fits easily
ROUND = os.environ.get("VPPT_BENCH_ROUND", "r05")
COMPLETE_OUT = os.path.join(REPO, f"BENCH_{ROUND}_manual_tpu.json")
PARTIAL_OUT = os.path.join(REPO, f"BENCH_{ROUND}_partial_tpu.json")


def log(msg: str) -> None:
    print(f"[{time.strftime('%H:%M:%S')}] {msg}", flush=True)


def probe() -> bool:
    # ONE probe definition for watcher and bench: bench.py's
    # _subprocess_probe (matmul executed in a throwaway process).
    # Import errors (a mid-edit working tree) count as probe-failed —
    # a detached watcher must survive them.
    try:
        if REPO not in sys.path:
            sys.path.insert(0, REPO)
        from bench import _subprocess_probe

        return _subprocess_probe(PROBE_TIMEOUT_S)
    except Exception as e:  # noqa: BLE001 — keep watching
        log(f"probe import/run failed: {type(e).__name__}: {e}")
        return False


def head_commit() -> str:
    return subprocess.run(
        ["git", "-C", REPO, "rev-parse", "HEAD"],
        capture_output=True, text=True, check=True,
    ).stdout.strip()


def snapshot_head(dst: str) -> None:
    ar = subprocess.Popen(["git", "-C", REPO, "archive", "HEAD"],
                          stdout=subprocess.PIPE)
    subprocess.run(["tar", "-x", "-C", dst], stdin=ar.stdout, check=True)
    ar.wait()
    if ar.returncode:
        raise RuntimeError(f"git archive rc={ar.returncode}")


def run_capture() -> None:
    commit = head_commit()
    tmp = tempfile.mkdtemp(prefix="bench_snap_")
    sidecar = os.path.join(tmp, "progress.json")
    try:
        snapshot_head(tmp)
        log(f"tunnel up — benching snapshot of {commit[:10]} in {tmp}")
        t0 = time.time()
        try:
            # --inner: the watcher IS the supervisor here (deadline
            # kill + sidecar salvage below); bench.py's own supervisor
            # mode would nest a second cpu-fill run inside our window
            proc = subprocess.run(
                [sys.executable, "bench.py", "--inner",
                 "--progress-out", sidecar],
                cwd=tmp, capture_output=True, text=True,
                timeout=BENCH_DEADLINE_S,
            )
            out_lines = [l for l in proc.stdout.splitlines() if l.strip()]
            timed_out = False
        except subprocess.TimeoutExpired as e:
            out_lines = []
            timed_out = True
            log(f"bench hit {BENCH_DEADLINE_S}s deadline — killed "
                f"(stderr tail: {str(e.stderr)[-200:] if e.stderr else ''})")
        wall = round(time.time() - t0, 1)

        result = None
        if out_lines:
            try:
                result = json.loads(out_lines[-1])
            except json.JSONDecodeError:
                log(f"unparseable bench stdout tail: {out_lines[-1][:200]}")
        if result and "error" not in result and \
                result.get("details", {}).get("backend") == "tpu":
            result["note"] = (
                f"Full-bench TPU capture by tools/tpu_watch.py from a "
                f"git-archive snapshot of commit {commit} (no working-tree "
                f"drift), {time.strftime('%Y-%m-%d %H:%M UTC', time.gmtime())}, "
                f"wall {wall}s, load_at_start in details. Re-run: git "
                f"archive + python bench.py at that commit.")
            with open(COMPLETE_OUT, "w") as f:
                json.dump(result, f, indent=1)
            rc = subprocess.run(
                ["git", "-C", REPO, "add", COMPLETE_OUT]).returncode
            rc |= subprocess.run(
                ["git", "-C", REPO, "commit", "-m",
                 f"Real-TPU bench capture {ROUND} (watcher, "
                 f"snapshot of {commit[:10]})",
                 "--", COMPLETE_OUT]).returncode
            if rc == 0:
                log(f"COMPLETE capture committed ({wall}s)")
            else:
                # capture is on disk either way (the round driver
                # commits uncommitted work); do not claim otherwise
                log(f"COMPLETE capture WRITTEN but git commit failed "
                    f"rc={rc} ({wall}s) — left for the round driver")
            return
        # partial: keep the furthest sidecar seen so far
        part = {}
        if os.path.exists(sidecar):
            try:
                part = json.load(open(sidecar))
            except json.JSONDecodeError:
                part = {}
        if result and "error" in result:
            log(f"bench errored: {result['error'][:200]}")
        if part.get("backend") != "tpu":
            log(f"no TPU partial to keep (backend={part.get('backend')}, "
                f"timed_out={timed_out})")
            return
        prev_keys = -1
        if os.path.exists(PARTIAL_OUT):
            try:
                prev_keys = len(json.load(open(PARTIAL_OUT)))
            except (json.JSONDecodeError, OSError):
                pass
        if len(part) + 2 > prev_keys:
            part["note"] = (
                f"PARTIAL TPU capture (tunnel wedged mid-run, watchdog "
                f"kill at {wall}s): every key here completed on backend="
                f"tpu before the wedge. Snapshot of commit {commit}, "
                f"{time.strftime('%Y-%m-%d %H:%M UTC', time.gmtime())}.")
            part["commit"] = commit
            with open(PARTIAL_OUT, "w") as f:
                json.dump(part, f, indent=1)
            rc = subprocess.run(
                ["git", "-C", REPO, "add", PARTIAL_OUT]).returncode
            rc |= subprocess.run(
                ["git", "-C", REPO, "commit", "-m",
                 "Partial TPU bench sections salvaged by the recovery "
                 "watcher", "--", PARTIAL_OUT]).returncode
            log(f"partial capture kept ({len(part)} keys, {wall}s, "
                f"commit rc={rc})")
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def main() -> None:
    log(f"watcher up (pid {os.getpid()}), repo {REPO}")
    while True:
        if os.path.exists(COMPLETE_OUT):
            log("complete capture exists — watcher done")
            return
        if probe():
            try:
                run_capture()
            except Exception as e:  # noqa: BLE001 — keep watching
                log(f"capture attempt failed: {type(e).__name__}: {e}")
        # sleep on EVERY iteration: a failed capture attempt (snapshot
        # error, CPU fallback) must not spin probe->capture->probe and
        # keep poking a grant that needs minutes unpoked to heal
        time.sleep(PROBE_INTERVAL_S)


if __name__ == "__main__":
    main()
