#!/usr/bin/env python
"""Config-knob autotuner (ISSUE 16): sweep the geometry knobs whose
best value is a property of the BACKEND, not of the policy — measure
each candidate with the real fused step / lookup / ring machinery and
emit a per-backend profile the agent loads as per-key DEFAULTS
(``tuned_profile:`` in the YAML; cmd/config.py apply_tuned_profile —
any knob the YAML sets explicitly wins).

Swept knobs:

  dataplane.sess_ways            {2, 4, 8}        fused-step ns/pkt
  dataplane.telemetry_sketch_*   (rows, cols) grid; "full"-telemetry
                                 step ns/pkt (the count-min geometry
                                 trades accuracy for VMEM bandwidth)
  env.VPPT_LPM_HINT_MIN          {1024, 8192, 65536}  LPM lookup
                                 ns/pkt (the stride-hint engage
                                 threshold: ops/lpm.py lpm_hint_min)
  io.io_ring_slots/_windows      {(8,2), (16,2), (16,4)}  persistent
                                 single-window exchange µs

The profile's ``floor_us`` is the measured p50 single-frame step
latency at the tuned knobs — the governor's achievable-latency floor:
``io.latency_slo_us`` below it is clamped UP at config load (an SLO
the hardware cannot meet pins the governor at the 1-slot floor
forever, shedding for nothing).

Profile shape (tuned/<backend>.json)::

    {"backend": "...", "generated_by": "tools/autotune.py",
     "knobs": {"dataplane": {...}, "io": {...}, "env": {...}},
     "measured": {...per-candidate numbers...}, "floor_us": ...}

``--check <path>`` validates a committed profile round-trips through
AgentConfig.from_dict (every knob lands on the built config; shape
and section errors are refused) — ``make autotune-check`` runs it
against the committed tuned/cpu.json.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

import numpy as np  # noqa: E402


# ---------------------------------------------------------------- sweep

def _build_dp(**overrides):
    from vpp_tpu.pipeline.dataplane import Dataplane
    from vpp_tpu.pipeline.tables import DataplaneConfig
    from vpp_tpu.pipeline.vector import Disposition

    cfg = DataplaneConfig(
        max_tables=2, max_rules=16, max_global_rules=64, max_ifaces=8,
        fib_slots=256, sess_slots=1 << 12, nat_mappings=4,
        nat_backends=4, **overrides)
    dp = Dataplane(cfg)
    uplink = dp.add_uplink()
    dp.builder.add_route("10.1.1.0/24", 1, Disposition.LOCAL)
    dp.builder.add_route("0.0.0.0/0", uplink, Disposition.REMOTE,
                         node_id=1)
    dp.swap()
    return dp, uplink


def _traffic(n, uplink, seed=7):
    import jax.numpy as jnp

    from vpp_tpu.pipeline.vector import FLAG_VALID, PacketVector, ip4

    rng = np.random.default_rng(seed)
    return PacketVector(
        src_ip=jnp.asarray(rng.integers(1, 1 << 30, n).astype(np.uint32)),
        dst_ip=jnp.asarray((ip4("10.1.1.0")
                            + rng.integers(2, 250, n)).astype(np.uint32)),
        proto=jnp.full((n,), 6, jnp.int32),
        sport=jnp.asarray(rng.integers(1024, 65000, n).astype(np.int32)),
        dport=jnp.full((n,), 80, jnp.int32),
        ttl=jnp.full((n,), 64, jnp.int32),
        pkt_len=jnp.full((n,), 512, jnp.int32),
        rx_if=jnp.full((n,), uplink, jnp.int32),
        flags=jnp.full((n,), FLAG_VALID, jnp.int32),
    )


def _step_ns_pkt(dp, pkts, batch, iters, warmup=2):
    import jax

    for i in range(warmup):
        r = dp.process(pkts, now=1 + i)
    jax.block_until_ready(r.disp)
    t0 = time.perf_counter()
    for i in range(iters):
        r = dp.process(pkts, now=10 + i)
    jax.block_until_ready(r.disp)
    return (time.perf_counter() - t0) / iters / batch * 1e9


def sweep_sess_ways(batch, iters, log):
    """Set-associativity of the session table: more ways = fewer
    collision misses but a wider probe/election per packet."""
    measured = {}
    for ways in (2, 4, 8):
        dp, uplink = _build_dp(sess_ways=ways)
        pkts = _traffic(batch, uplink)
        measured[str(ways)] = round(_step_ns_pkt(dp, pkts, batch, iters), 1)
        log(f"  sess_ways={ways}: {measured[str(ways)]} ns/pkt")
    best = min(measured, key=lambda k: measured[k])
    return int(best), measured


def sweep_sketch(batch, iters, log):
    """Count-min sketch geometry under "full" telemetry: depth buys
    collision confidence, width buys per-row accuracy — both cost
    VMEM bandwidth in the fused step."""
    measured = {}
    for rows, cols in ((2, 2048), (4, 4096), (4, 8192)):
        dp, uplink = _build_dp(telemetry="full",
                               telemetry_sketch_rows=rows,
                               telemetry_sketch_cols=cols)
        pkts = _traffic(batch, uplink)
        measured[f"{rows}x{cols}"] = round(
            _step_ns_pkt(dp, pkts, batch, iters), 1)
        log(f"  sketch {rows}x{cols}: {measured[f'{rows}x{cols}']} ns/pkt")
    best = min(measured, key=lambda k: measured[k])
    r, c = (int(x) for x in best.split("x"))
    return (r, c), measured


def sweep_lpm_hint(batch, iters, log):
    """Stride-hint engage threshold (ops/lpm.py lpm_hint_min): hints
    shrink the per-length bisection at the cost of one extra gather —
    below some plane size the full bisection is already cheaper."""
    import jax

    from vpp_tpu.ops.lpm import fib_lookup_lpm
    from vpp_tpu.pipeline.vector import Disposition

    measured = {}
    saved = os.environ.get("VPPT_LPM_HINT_MIN")
    try:
        for hint_min in (1024, 8192, 65536):
            os.environ["VPPT_LPM_HINT_MIN"] = str(hint_min)
            dp, uplink = _build_dp(fib_impl="lpm")
            rng = np.random.default_rng(5)
            for _ in range(60):
                plen = int(rng.choice([8, 16, 24, 24, 32]))
                net = (int(rng.integers(0, 1 << 32))
                       & (0xFFFFFFFF << (32 - plen)))
                dp.builder.add_route(
                    f"{net >> 24 & 255}.{net >> 16 & 255}."
                    f"{net >> 8 & 255}.{net & 255}/{plen}",
                    1, Disposition.LOCAL)
            dp.swap()
            pkts = _traffic(batch, uplink, seed=9)
            fn = jax.jit(fib_lookup_lpm)
            jax.block_until_ready(fn(dp.tables, pkts))
            t0 = time.perf_counter()
            for _ in range(iters):
                r = fn(dp.tables, pkts)
            jax.block_until_ready(r)
            measured[str(hint_min)] = round(
                (time.perf_counter() - t0) / iters / batch * 1e9, 1)
            log(f"  lpm hint_min={hint_min}: {measured[str(hint_min)]} "
                "ns/pkt")
    finally:
        if saved is None:
            os.environ.pop("VPPT_LPM_HINT_MIN", None)
        else:
            os.environ["VPPT_LPM_HINT_MIN"] = saved
    best = min(measured, key=lambda k: measured[k])
    return int(best), measured


def sweep_ring(iters, log):
    """Persistent device-ring geometry: slots amortize the per-window
    exchange, windows deepen the refill overlap — measured as the
    single-window ping-pong µs (the latency-floor quantum)."""
    from vpp_tpu.pipeline.dataplane import pack_packet_columns
    from vpp_tpu.pipeline.persistent import PersistentPump

    frame = 64
    measured = {}
    for slots, windows in ((8, 2), (16, 2), (16, 4)):
        pump = None
        try:
            dp, uplink = _build_dp()
            pv = _traffic(frame, uplink, seed=13)
            cols = {f: np.asarray(getattr(pv, f))
                    for f in ("src_ip", "dst_ip", "proto", "sport",
                              "dport", "ttl", "pkt_len", "rx_if",
                              "flags")}
            flat = np.zeros((5, frame), np.int32)
            pack_packet_columns(flat.view(np.uint32), cols, frame)
            pump = PersistentPump(dp.tables, batch=frame,
                                  classifier=dp.classifier_impl,
                                  skip_local=dp._skip_local,
                                  ring_slots=slots,
                                  ring_windows=windows)
            pump.start()
            pump.submit(flat, now=1)
            pump.result(timeout=600)
            lat = []
            for i in range(iters):
                t0 = time.perf_counter()
                pump.submit(flat, now=2 + i)
                pump.result(timeout=120)
                lat.append(time.perf_counter() - t0)
            measured[f"{slots}x{windows}"] = round(
                float(np.percentile(np.array(lat) * 1e6, 50)), 1)
            log(f"  ring {slots}x{windows}: "
                f"{measured[f'{slots}x{windows}']} us/window")
        except Exception as e:  # noqa: BLE001 — best-effort lever
            measured[f"{slots}x{windows}"] = f"error: {type(e).__name__}"
            log(f"  ring {slots}x{windows}: FAILED ({type(e).__name__})")
        finally:
            if pump is not None:
                try:
                    pump.stop()
                except Exception:  # noqa: BLE001 — already recorded
                    pass
    ok = {k: v for k, v in measured.items() if isinstance(v, float)}
    if not ok:
        return None, measured
    best = min(ok, key=lambda k: ok[k])
    s, w = (int(x) for x in best.split("x"))
    return (s, w), measured


def measure_floor(knobs, log):
    """p50 single-frame step latency at the TUNED dataplane knobs —
    the governor's achievable floor on this backend."""
    import jax

    frame = 64
    dp, uplink = _build_dp(**knobs)
    pkts = _traffic(frame, uplink, seed=11)
    lat = []
    for i in range(3):
        r = dp.process(pkts, now=1 + i)
    jax.block_until_ready(r.disp)
    for i in range(30):
        t0 = time.perf_counter()
        r = dp.process(pkts, now=10 + i)
        jax.block_until_ready(r.disp)
        lat.append(time.perf_counter() - t0)
    floor = round(float(np.percentile(np.array(lat) * 1e6, 50)), 1)
    log(f"  floor: {floor} us (p50, {frame}-pkt frame)")
    return floor


def run_sweep(args, log) -> dict:
    import jax

    backend = jax.default_backend()
    log(f"autotune: backend={backend} batch={args.batch} "
        f"iters={args.iters}")
    knobs_dp, knobs_io, knobs_env, measured = {}, {}, {}, {}

    log("sweep: dataplane.sess_ways")
    ways, m = sweep_sess_ways(args.batch, args.iters, log)
    knobs_dp["sess_ways"] = ways
    measured["sess_ways_ns_pkt"] = m

    log("sweep: dataplane.telemetry_sketch_{rows,cols}")
    (rows, cols), m = sweep_sketch(args.batch, args.iters, log)
    knobs_dp["telemetry_sketch_rows"] = rows
    knobs_dp["telemetry_sketch_cols"] = cols
    measured["sketch_ns_pkt"] = m

    log("sweep: VPPT_LPM_HINT_MIN")
    hint, m = sweep_lpm_hint(args.batch, args.iters, log)
    knobs_env["VPPT_LPM_HINT_MIN"] = str(hint)
    measured["lpm_hint_ns_pkt"] = m

    if args.skip_ring:
        log("sweep: io ring geometry SKIPPED (--skip-ring)")
        measured["ring_us_window"] = "skipped"
    else:
        log("sweep: io.io_ring_{slots,windows}")
        geo, m = sweep_ring(max(4, args.iters), log)
        measured["ring_us_window"] = m
        if geo is not None:
            knobs_io["io_ring_slots"], knobs_io["io_ring_windows"] = geo

    log("measure: governor floor at tuned knobs")
    floor = measure_floor({"sess_ways": knobs_dp["sess_ways"]}, log)

    knobs = {"dataplane": knobs_dp}
    if knobs_io:
        knobs["io"] = knobs_io
    if knobs_env:
        knobs["env"] = knobs_env
    return {
        "backend": backend,
        "generated_by": "tools/autotune.py",
        "knobs": knobs,
        "measured": measured,
        "floor_us": floor,
    }


# ---------------------------------------------------------------- check

def check_profile(path: str) -> list:
    """Round-trip a committed profile through the SAME loader the
    agent boots with: every knob must land on the built AgentConfig
    (or, for env knobs, be applied to the environment). Returns
    problems — ``make autotune-check`` fails on any."""
    from vpp_tpu.cmd.config import AgentConfig, load_tuned_profile

    problems = []
    try:
        prof = load_tuned_profile(path)
    except ValueError as e:
        return [f"autotune-check: {e}"]
    if prof is None:
        return [f"autotune-check: {path}: empty path"]
    for key in ("backend", "knobs", "floor_us"):
        if key not in prof:
            problems.append(f"autotune-check: {path}: missing {key!r}")
    if not isinstance(prof.get("floor_us"), (int, float)):
        problems.append(
            f"autotune-check: {path}: floor_us not numeric "
            f"({prof.get('floor_us')!r})")
    saved_env = dict(os.environ)
    try:
        cfg = AgentConfig.from_dict({"tuned_profile": path})
    except Exception as e:  # noqa: BLE001 — report, not raise
        return problems + [
            f"autotune-check: {path}: AgentConfig.from_dict refused "
            f"the profile: {type(e).__name__}: {e}"]
    for section, obj in (("dataplane", cfg.dataplane), ("io", cfg.io)):
        for k, v in (prof.get("knobs") or {}).get(section, {}).items():
            got = getattr(obj, k, None)
            if got != v:
                problems.append(
                    f"autotune-check: {path}: knobs.{section}.{k}={v!r} "
                    f"did not land on the built config (got {got!r})")
    for k, v in (prof.get("knobs") or {}).get("env", {}).items():
        if os.environ.get(k) != str(v):
            problems.append(
                f"autotune-check: {path}: knobs.env.{k}={v!r} was not "
                f"applied to the environment")
        if saved_env.get(k) is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = saved_env[k]
    # the floor must clamp an under-floor SLO UP through the loader
    floor = prof.get("floor_us")
    if isinstance(floor, (int, float)) and floor > 1:
        cfg2 = AgentConfig.from_dict({
            "tuned_profile": path, "io": {"latency_slo_us": 1}})
        if cfg2.io.latency_slo_us < floor:
            problems.append(
                f"autotune-check: {path}: io.latency_slo_us=1 was not "
                f"clamped up to floor_us={floor} "
                f"(got {cfg2.io.latency_slo_us})")
    return problems


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--out", default=None,
                    help="profile path (default tuned/<backend>.json)")
    ap.add_argument("--check", default=None, metavar="PATH",
                    help="validate a committed profile instead of "
                    "sweeping (make autotune-check)")
    ap.add_argument("--batch", type=int, default=2048)
    ap.add_argument("--iters", type=int, default=8)
    ap.add_argument("--skip-ring", action="store_true",
                    help="skip the persistent-ring geometry sweep "
                    "(slow on CPU fallback)")
    ap.add_argument("--quiet", action="store_true")
    args = ap.parse_args()

    def log(msg):
        if not args.quiet:
            print(msg, file=sys.stderr)

    if args.check:
        problems = check_profile(args.check)
        for p in problems:
            print(p, file=sys.stderr)
        if not problems:
            log(f"autotune-check: {args.check}: OK")
        return 1 if problems else 0

    profile = run_sweep(args, log)
    out = args.out or str(REPO / "tuned" / f"{profile['backend']}.json")
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "w") as f:
        json.dump(profile, f, indent=2, sort_keys=True)
        f.write("\n")
    log(f"wrote {out}")
    print(json.dumps({"profile": out, "floor_us": profile["floor_us"],
                      "knobs": profile["knobs"]}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
